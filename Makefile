# Top-level build/test entry points (reference C9 analog: the reference
# builds each program with documented gcc/nvcc one-liners; here one Makefile
# drives the native library, tests, benchmarks, and dataset regeneration).

PYTHON ?= python
OBS_SMOKE ?= /tmp/gauss_obs_check.jsonl
SERVE_SMOKE ?= /tmp/gauss_serve_check
FAULTS_SMOKE ?= /tmp/gauss_faults_check
STRUCT_SMOKE ?= /tmp/gauss_structure_check
TUNE_SMOKE ?= /tmp/gauss_tune_check
LIVE_SMOKE ?= /tmp/gauss_live_check
ABFT_SMOKE ?= /tmp/gauss_abft_check
DURABLE_SMOKE ?= /tmp/gauss_durable_check
OUTOFCORE_SMOKE ?= /tmp/gauss_outofcore_check
MESH_SMOKE ?= /tmp/gauss_mesh_serve_check
LINT_SMOKE ?= /tmp/gauss_lint_check
FLIGHT_SMOKE ?= /tmp/gauss_flight_check
PROF_SMOKE ?= /tmp/gauss_prof_check
SPARSE_SMOKE ?= /tmp/gauss_sparse_check
REPLICA_SMOKE ?= /tmp/gauss_replica_check
POISON_SMOKE ?= /tmp/gauss_poison_check

.PHONY: all native test bench datasets obs-check serve-check faults-check \
	structure-check sparse-check tune-check live-check abft-check \
	durable-check outofcore-check mesh-serve-check lint-check flight-check \
	prof-check replica-check poison-check clean

# The timing-gated gates (obs/serve/structure/tune/faults/live/abft/
# durable-check)
# are regress-gated through obs.regress noise bands calibrated on an
# UNCONTENDED box: running them concurrently — with each other, or with
# the test suite — pushes s_per_case / s_per_solve out of band and fails
# gates on scheduler contention, not code (documented on this box; the
# ISSUE-11 ordering note). .NOTPARALLEL keeps `make -j obs-check
# serve-check ...` serial within one make invocation; don't run several
# make processes against these targets at once either.
.NOTPARALLEL:

all: native

native:
	$(MAKE) -C gauss_tpu/native/src

test: native
	$(PYTHON) -m pytest tests/ -q

bench:
	$(PYTHON) bench.py

# The observability gate (CI-callable): the regression sentinel against the
# committed history (the latest BENCH records must stay inside the epoch-
# noise band), the RATCHET leg (the committed record gated against the
# best-ever baseline with its tightened per-metric ceiling — the same
# evaluate_ratchet path bench.py --regress applies to fresh headlines, so
# the ratchet gate is exercised in CI), then a live --metrics-out run
# smoke-tested through the machine-readable summarizer and the
# Chrome-trace exporter, and finally the DOCTOR gate: the smoke run's
# span stream diffed against the committed best-prior epoch — the
# host_group_step / hook_sync leaves that absorbed 93% of the r3->r5
# regression (reports/doctor_r3_vs_r5.json) must NOT reappear on the
# plain (hooks-off) path. The throughput leg (ISSUE 11) runs a fresh
# batched solves/sec epoch at the smallest record size and gates it
# against the 3 committed epochs in reports/history.jsonl AND the
# throughput ratchet (RATCHET_BASELINES/RATCHET_CEILINGS) — both records,
# latency and throughput, are regress-gated from PR 11 on. Best-of-reps
# timing: only a systematic slowdown fails, not one noisy rep.
obs-check:
	$(PYTHON) -m gauss_tpu.obs.regress check BENCH_r04.json BENCH_r05.json \
	  --history reports/history.jsonl
	$(PYTHON) -m gauss_tpu.obs.regress check BENCH_r03.json --ratchet \
	  --history reports/history.jsonl
	JAX_PLATFORMS=cpu $(PYTHON) -m gauss_tpu.bench.throughput --ns 256 \
	  --batch 8 --reps 2 --seed 258458 --regress-check
	rm -f $(OBS_SMOKE)
	JAX_PLATFORMS=cpu $(PYTHON) -m gauss_tpu.cli.gauss_internal -s 64 -t 2 \
	  --backend tpu-unblocked --verify --metrics-out $(OBS_SMOKE)
	$(PYTHON) -m gauss_tpu.obs.summarize $(OBS_SMOKE) --json > /dev/null
	$(PYTHON) -m gauss_tpu.obs.trace $(OBS_SMOKE) -o $(OBS_SMOKE).trace.json
	$(PYTHON) -m gauss_tpu.obs.doctor reports/doctor_r3like.jsonl \
	  $(OBS_SMOKE) --forbid host_group_step,hook_sync > /dev/null

# The serving gate (CI-callable): a CPU smoke load through the batched
# serving layer — 50 mixed-size requests over small buckets, every solution
# verified at the 1e-4 gate (exit 2 on any incorrect), the run gated
# against the regression history (exit 1 out-of-band) — then the recorded
# stream is asserted to carry a non-empty serving summary.
serve-check:
	rm -rf $(SERVE_SMOKE) && mkdir -p $(SERVE_SMOKE)
	JAX_PLATFORMS=cpu $(PYTHON) -m gauss_tpu.serve.cli --requests 50 \
	  --warmup 8 --ladder 32,64,128 --seed 258458 \
	  --mix "random:24*2,random:60,random:100,internal:48" \
	  --metrics-out $(SERVE_SMOKE)/serve.jsonl \
	  --summary-json $(SERVE_SMOKE)/summary.json --regress-check
	$(PYTHON) -m gauss_tpu.obs.summarize $(SERVE_SMOKE)/serve.jsonl --json \
	  | $(PYTHON) -c "import json,sys; runs=json.load(sys.stdin); \
	sv=[r['serving'] for r in runs.values() if r.get('serving')]; \
	assert sv and sv[0]['requests'].get('ok', 0) >= 50, sv; \
	print('serve-check: serving summary ok:', sv[0]['requests'])"

# The resilience gate (CI-callable): a CPU chaos smoke campaign — 200
# seeded fault cases across both engines plus serve, checkpoint, and
# supervised-fleet phases (small n, fault paths not FLOPs) asserting the
# chaos invariant (every injected fault recovered-and-verified or a typed
# error; exit 2 on a silent wrong answer), gated against the regression
# history (exit 1 when recovery depth / typed-error rate / per-case cost
# leave the band), then the recorded stream is asserted to carry a
# resilience summary. The second leg is the bounded-time multihost fleet
# smoke: a 2-worker supervised solve with worker 1 KILLED mid-factorization
# must restart-and-resume from the sharded checkpoint, verify at 1e-4, and
# finish inside the timeout (a hang fails the gate by construction); its
# recovery metrics (restarts, resume latency, rung) append to
# reports/history.jsonl and are gated by obs.regress.
faults-check:
	rm -rf $(FAULTS_SMOKE) && mkdir -p $(FAULTS_SMOKE)
	JAX_PLATFORMS=cpu $(PYTHON) -m gauss_tpu.resilience.chaos --cases 200 \
	  --serve-requests 30 --seed 258458 --tmpdir $(FAULTS_SMOKE) \
	  --metrics-out $(FAULTS_SMOKE)/chaos.jsonl \
	  --summary-json $(FAULTS_SMOKE)/summary.json --regress-check
	$(PYTHON) -m gauss_tpu.obs.summarize $(FAULTS_SMOKE)/chaos.jsonl --json \
	  | $(PYTHON) -c "import json,sys; runs=json.load(sys.stdin); \
	rs=[r['resilience'] for r in runs.values() if r.get('resilience')]; \
	assert rs and rs[0]['injections']['total'] >= 200, rs; \
	print('faults-check: resilience summary ok:', rs[0]['injections']['total'], 'injections')"
	timeout -k 10 240 env JAX_PLATFORMS=cpu $(PYTHON) -m \
	  gauss_tpu.resilience.fleet -s 64 --workers 2 --panel 16 --chunk 1 \
	  --seed 258458 --inject 'fleet.worker.group=kill:skip=2' \
	  --inject-worker 1 --stall-after 5 --job-timeout 180 \
	  --metrics-out $(FAULTS_SMOKE)/fleet.jsonl \
	  --summary-json $(FAULTS_SMOKE)/fleet.json --history --regress-check
	$(PYTHON) -m gauss_tpu.obs.summarize $(FAULTS_SMOKE)/fleet.jsonl --json \
	  | $(PYTHON) -c "import json,sys; runs=json.load(sys.stdin); \
	fl=[r['fleet'] for r in runs.values() if r.get('fleet')]; \
	assert fl and fl[0]['restarts'] >= 1 and fl[0]['solves'] == 1, fl; \
	print('faults-check: fleet summary ok:', fl[0])"

# The structure gate (CI-callable): detect -> route -> engine -> 1e-4
# verify across all four structure classes (SPD/Cholesky, banded,
# block-diagonal, dense) on the deterministic generators, exit 2 on any
# misroute or verification failure, gated against the regression history
# (exit 1 out-of-band: a class silently demoting back to dense LU moves
# its flops_ratio/s_per_solve out of band), then the recorded stream is
# asserted to carry a structure-lanes summary with zero demotions.
structure-check:
	rm -rf $(STRUCT_SMOKE) && mkdir -p $(STRUCT_SMOKE)
	JAX_PLATFORMS=cpu $(PYTHON) -m gauss_tpu.structure.check \
	  --spd-n 96 --banded-n 512 --banded-bw 1 \
	  --blockdiag-n 96 --block 16 --dense-n 96 --seed 258458 \
	  --metrics-out $(STRUCT_SMOKE)/structure.jsonl \
	  --summary-json $(STRUCT_SMOKE)/summary.json --regress-check
	$(PYTHON) -m gauss_tpu.obs.summarize $(STRUCT_SMOKE)/structure.jsonl \
	  --json | $(PYTHON) -c "import json,sys; runs=json.load(sys.stdin); \
	st=[r['structure'] for r in runs.values() if r.get('structure')]; \
	assert st and st[0]['solves'] >= 4 and st[0]['demotions'] == 0, st; \
	print('structure-check: structure summary ok:', st[0]['engines'])"

# The sparse-plane gate (CI-callable): coordinate classification ->
# sparse routing (no demotion) -> CG/GMRES/BiCGStab each verified at the
# 1e-4 gate, then the n=100k no-densify leg — assembled and CG-solved
# with the process peak RSS asserted under a budget the dense operand
# alone (80 GB) exceeds tenfold (exit 2 on any leg), gated against the
# regression history (kind=sparse_solve; exit 1 when per-method seconds/
# iterations or the giant leg's peak bytes leave the band), then the
# recorded stream is asserted to carry a sparse summary with every
# attempt converged.
sparse-check:
	rm -rf $(SPARSE_SMOKE) && mkdir -p $(SPARSE_SMOKE)
	JAX_PLATFORMS=cpu $(PYTHON) -m gauss_tpu.sparse.check \
	  --smoke-n 640 --nnz-per-row 6 \
	  --giant-n 100000 --giant-nnz-per-row 20 --seed 258458 \
	  --metrics-out $(SPARSE_SMOKE)/sparse.jsonl \
	  --summary-json $(SPARSE_SMOKE)/summary.json --regress-check
	$(PYTHON) -m gauss_tpu.obs.summarize $(SPARSE_SMOKE)/sparse.jsonl \
	  --json | $(PYTHON) -c "import json,sys; runs=json.load(sys.stdin); \
	sp=[r['sparse'] for r in runs.values() if r.get('sparse')]; \
	assert sp and sp[0]['attempts'] >= 5 and all( \
	m['converged'] == m['attempts'] for m in sp[0]['methods'].values()), sp; \
	print('sparse-check: sparse summary ok:', \
	sorted(sp[0]['methods']))"

# The autotuner gate (CI-callable): micro-sweep (2 points per axis)
# through the real gauss-tune runner -> store written -> the tuned solve
# must consult the store (obs events), verify at 1e-4, and factor
# bit-identically to the explicit winning config -> serve warmup must pick
# up the tuned panel with an UNCHANGED cache key -> a second process
# sharing the persistent XLA compile cache must perform STRICTLY FEWER
# backend compiles than the first (obs xla.cache_miss accounting; exit 2
# on any assertion failure), gated against the regression history (exit 1
# when the sweep's winner or win-ratio leaves the band), then the recorded
# stream is asserted to carry a tuning summary with store consults.
tune-check:
	rm -rf $(TUNE_SMOKE) && mkdir -p $(TUNE_SMOKE)
	JAX_PLATFORMS=cpu $(PYTHON) -m gauss_tpu.tune.check --n 96 \
	  --seed 258458 --tmpdir $(TUNE_SMOKE) \
	  --metrics-out $(TUNE_SMOKE)/tune.jsonl \
	  --summary-json $(TUNE_SMOKE)/summary.json --regress-check
	$(PYTHON) -m gauss_tpu.obs.summarize $(TUNE_SMOKE)/tune.jsonl --json \
	  | $(PYTHON) -c "import json,sys; runs=json.load(sys.stdin); \
	tn=[r['tuning'] for r in runs.values() if r.get('tuning')]; \
	assert tn and tn[0]['store']['hits'] >= 1 and tn[0]['sweep']['points'] >= 1, tn; \
	print('tune-check: tuning summary ok:', tn[0]['store'])"

# The live-telemetry gate (CI-callable): a SolverServer with the live
# plane embedded (ephemeral /metrics port) is driven by a small loadgen
# mix; the Prometheus scrape totals must agree EXACTLY with the loadgen's
# final report (served/rejected/expired/failed/retries), every terminal
# status must fold into exactly one per-request trace, an on-demand
# /trace?batches=1 capture from the RUNNING server must contain the
# serve_batch_solve span, and a forced deadline-violation burst must FIRE
# the SLO burn-rate alert which then CLEARS under good traffic — then the
# recorded stream is asserted to carry the alert transitions, and
# gauss-top renders one frame from the committed-format exposition.
live-check:
	rm -rf $(LIVE_SMOKE) && mkdir -p $(LIVE_SMOKE)
	timeout -k 10 300 env JAX_PLATFORMS=cpu $(PYTHON) -m \
	  gauss_tpu.obs.livecheck --requests 40 --seed 258458 \
	  --metrics-out $(LIVE_SMOKE)/live.jsonl \
	  --summary-json $(LIVE_SMOKE)/summary.json
	$(PYTHON) -m gauss_tpu.obs.summarize $(LIVE_SMOKE)/live.jsonl --json \
	  | $(PYTHON) -c "import json,sys; runs=json.load(sys.stdin); \
	sl=[r['slo'] for r in runs.values() if r.get('slo')]; \
	assert sl and sl[0]['alerts'] >= 1 and sl[0]['unresolved'] == 0, sl; \
	print('live-check: slo summary ok:', sl[0])"
	$(PYTHON) -m gauss_tpu.obs.requesttrace $(LIVE_SMOKE)/live.jsonl \
	  --check > /dev/null

# The ABFT gate (CI-callable): the silent-data-corruption smoke campaign —
# >= 100 seeded on-device sdc_bitflip faults injected at panel-group
# boundaries of the checksum-carrying LU and Cholesky engines; every
# corruption must be DETECTED by the checksum invariant before the final
# residual gate, localized to its panel group, and recovered via the
# localized replay rung (bit-identical to an uninterrupted ABFT run) or
# ladder escalation for persistent faults (exit 2 on a missed detection,
# silent wrong answer, or bit-identity failure). The identity phase
# asserts abft=False paths stay BIT-IDENTICAL to the checksum-carrying
# forms' factors and records the plain-path s_per_solve as the
# zero-overhead regression sentinel (exit 1 when it leaves the noise
# band); the matmul phase asserts single-element GEMM corruption is
# corrected in place from the row x column checksum intersection. Then
# the recorded stream is asserted to carry an sdc summary.
abft-check:
	rm -rf $(ABFT_SMOKE) && mkdir -p $(ABFT_SMOKE)
	JAX_PLATFORMS=cpu $(PYTHON) -m gauss_tpu.resilience.abftcheck \
	  --cases 110 --seed 258458 \
	  --metrics-out $(ABFT_SMOKE)/abft.jsonl \
	  --summary-json $(ABFT_SMOKE)/summary.json --regress-check
	$(PYTHON) -m gauss_tpu.obs.summarize $(ABFT_SMOKE)/abft.jsonl --json \
	  | $(PYTHON) -c "import json,sys; runs=json.load(sys.stdin); \
	sd=[r['sdc'] for r in runs.values() if r.get('sdc')]; \
	assert sd and sd[0]['detections']['total'] >= 100 \
	  and sd[0]['injected']['total'] >= 100, sd; \
	print('abft-check: sdc summary ok:', sd[0]['detections'])"

# The durability gate (CI-callable): the kill-the-server chaos campaign —
# >= 30 seeded crash/torn-write/resume cases (in-process batch-boundary
# crashes + REAL os._exit subprocess kills via the server_kill /
# journal_torn_write fault kinds, plus a supervised auto-restart leg)
# against the write-ahead request journal; the invariant is 100% of
# admitted requests reaching exactly one terminal status (served results
# re-verified by the campaign at the 1e-4 gate from the journaled
# operands), zero duplicate terminals, and zero duplicate solves under
# idempotent resubmission (exit 2 on any violation). The overhead phase
# measures journal-on seconds-per-request against the same journal-off
# plan (regress-gated; journal-off stays inside the pre-existing
# serve-check band). Then the recorded stream is asserted to carry a
# durability summary and every trace in it must hold exactly one terminal
# ACROSS the in-process crashes (requesttrace --check — replayed
# terminals complete the original trace trees). Timing-gated: honor the
# serial-ordering note above.
durable-check:
	rm -rf $(DURABLE_SMOKE) && mkdir -p $(DURABLE_SMOKE)
	timeout -k 10 540 env JAX_PLATFORMS=cpu $(PYTHON) -m \
	  gauss_tpu.serve.durablecheck --cases 28 --seed 258458 \
	  --tmpdir $(DURABLE_SMOKE) \
	  --metrics-out $(DURABLE_SMOKE)/durable.jsonl \
	  --summary-json $(DURABLE_SMOKE)/summary.json --regress-check
	$(PYTHON) -m gauss_tpu.obs.summarize $(DURABLE_SMOKE)/durable.jsonl \
	  --json | $(PYTHON) -c "import json,sys; runs=json.load(sys.stdin); \
	du=[r['durability'] for r in runs.values() if r.get('durability')]; \
	assert du and du[0]['resumes']['replayed'] >= 10 \
	  and du[0]['deduped'] >= 1, du; \
	print('durable-check: durability summary ok:', du[0]['resumes'])"
	$(PYTHON) -m gauss_tpu.obs.requesttrace $(DURABLE_SMOKE)/durable.jsonl \
	  --check > /dev/null

# The out-of-core gate (CI-callable): the host-streamed blocked LU —
# only the active panel group + a bounded trailing tile window device-
# resident, H2D/D2H double-buffered against compute — solved end to end
# on the CPU proxy and asserted on its three contracts: the 1e-4
# relative-residual gate, the MEASURED peak of the device-byte ledger
# under 50% of the full in-core working set (with the trailing region
# demonstrably tiled), and solve_handoff routing a forced-oversized
# no-mesh request onto the streamed lane (route event lane=outofcore on
# the recorded stream). Streamed s_per_solve, the stall fraction
# (1 - transfer/compute overlap), and the peak device fraction are
# regress-gated against the committed epochs. The acceptance-scale
# n=32768 leg runs via `--giant 32768` (minutes; not part of this gate).
# Timing-gated: honor the serial-ordering note above.
outofcore-check:
	rm -rf $(OUTOFCORE_SMOKE) && mkdir -p $(OUTOFCORE_SMOKE)
	timeout -k 10 420 env JAX_PLATFORMS=cpu $(PYTHON) -m \
	  gauss_tpu.outofcore.check --seed 258458 \
	  --metrics-out $(OUTOFCORE_SMOKE)/outofcore.jsonl \
	  --summary-json $(OUTOFCORE_SMOKE)/summary.json --regress-check
	$(PYTHON) -m gauss_tpu.obs.summarize $(OUTOFCORE_SMOKE)/outofcore.jsonl \
	  > /dev/null

# The mesh-serving gate (CI-callable): the multi-lane serving plane on
# the 8-virtual-device CPU proxy — every request served + verified at
# 1e-4 over 4 lanes x 2-device mesh slices (batch axis NamedSharding-
# sharded), EVERY lane dispatching >= 1 batch, work stealing engaging
# under the skewed token mix, and the Prometheus scrape totals equal to
# the loadgen's client-side ledger EXACTLY; then the continuous-batching
# A/B: same open-loop mix, same lanes, same formation window, CB
# (in-flight admission + deadline-aware slot closing) must beat the
# fixed drain-cycle discipline on served solves/sec at equal-or-better
# p99 (the drain cycle lingers blind and sheds deadline traffic). The
# honest note rides in the summary: the 1-core proxy measures dispatch/
# batching efficiency, not MXU scaling. Every trace in the recorded
# stream must hold exactly one terminal (stolen requests keep the
# exactly-once contract), the run is regress-gated (kind: mesh_serve, 3
# committed epochs), and the multi-lane throughput-record leg
# (tput:float32/n256/b8/l4) runs fresh and is gated against its history
# + ratchet. Timing-gated: honor the serial-ordering note above.
mesh-serve-check:
	rm -rf $(MESH_SMOKE) && mkdir -p $(MESH_SMOKE)
	timeout -k 10 420 env JAX_PLATFORMS=cpu $(PYTHON) -m \
	  gauss_tpu.serve.meshcheck --seed 258458 \
	  --metrics-out $(MESH_SMOKE)/mesh.jsonl \
	  --summary-json $(MESH_SMOKE)/summary.json --regress-check
	$(PYTHON) -m gauss_tpu.obs.requesttrace $(MESH_SMOKE)/mesh.jsonl \
	  --check > /dev/null
	$(PYTHON) -m gauss_tpu.obs.summarize $(MESH_SMOKE)/mesh.jsonl --json \
	  | $(PYTHON) -c "import json,sys; runs=json.load(sys.stdin); \
	sv=[r['serving'] for r in runs.values() if r.get('serving')]; \
	assert sv and sv[0]['mesh']['steals'] >= 1 \
	  and len(sv[0]['mesh']['lane_batches']) >= 4, sv; \
	print('mesh-serve-check: serving mesh summary ok:', sv[0]['mesh'])"
	JAX_PLATFORMS=cpu $(PYTHON) -m gauss_tpu.bench.throughput --ns 256 \
	  --batch 8 --reps 2 --lanes 4 --seed 258458 --regress-check

# The static-analysis gate (CI-callable): gauss-lint runs the jaxpr
# auditor (every registered fast-path entry traced — callback-free plain
# path, bf16->f32 accumulation, f64 confinement, donation survival,
# registry completeness), the lockset checker (guarded-by annotations +
# the terminal-emit CAS rule over the serving core), and the drift lint
# (single-source tunables, API/OBSERVABILITY doc coverage, ratchet-vs-
# history existence, the x-or-Ctor() ban) against the COMMITTED EMPTY
# baseline — exit 1 on any new finding, with its file:line. The second
# leg regress-checks the per-pass finding counts against the committed
# 0-finding epochs in reports/history.jsonl, so the lint gate ratchets
# exactly like the perf gates. Not timing-gated (pure tracing/AST), but
# .NOTPARALLEL keeps it serial with the timing-gated targets anyway.
lint-check:
	rm -rf $(LINT_SMOKE) && mkdir -p $(LINT_SMOKE)
	JAX_PLATFORMS=cpu $(PYTHON) -m gauss_tpu.analysis.cli \
	  --json $(LINT_SMOKE)/lint.json --regress-check
	$(PYTHON) -m gauss_tpu.obs.regress check $(LINT_SMOKE)/lint.json \
	  --history reports/history.jsonl

# The flight-recorder gate (CI-callable): a journaled, flight-recording
# server child SIGKILLed (kill -9) mid-load once its mmap ring shows the
# batch budget; the resume run's automatic unclean_resume post-mortem
# bundle must pass gauss-debug --check and reconstruct the final >= 5
# batches with trace ids that cross-check against the journal, and an
# in-flight request set equal to the journal's unterminated admits
# EXACTLY (exit 2 on any miss). The torn-tail leg re-scans the ring cut
# at EVERY data-region byte offset (plus a wrapped-ring damage sweep):
# the scan must never raise and never fabricate a record. The overhead
# leg measures flight-on seconds-per-request against the same flight-off
# plan (best-of-2, warm shared cache) and gates it against the 3
# committed epochs AND the flight ratchet (the always-on ring's cost only
# ratchets down). The bundle capture fires inside the resume subprocess
# (not the gate's own obs stream), so the follow-up assertion reads the
# summary JSON, not a summarize section. Timing-gated: honor the
# serial-ordering note above.
flight-check:
	rm -rf $(FLIGHT_SMOKE) && mkdir -p $(FLIGHT_SMOKE)
	timeout -k 10 420 env JAX_PLATFORMS=cpu $(PYTHON) -m \
	  gauss_tpu.obs.flightcheck --seed 258458 --tmpdir $(FLIGHT_SMOKE) \
	  --metrics-out $(FLIGHT_SMOKE)/flight.jsonl \
	  --summary-json $(FLIGHT_SMOKE)/summary.json --regress-check
	$(PYTHON) -c "import json; s=json.load(open('$(FLIGHT_SMOKE)/summary.json')); \
	assert s['invariant_ok'], s; \
	k=s['kill']; assert k['cause'] == 'unclean_resume' and k['bundle_check_rc'] == 0, k; \
	print('flight-check: bundle %s reconstructed %d batch(es), %d in flight' \
	  % (k['bundle'].rsplit('/', 1)[-1], k['batches_reconstructed'], k['in_flight_at_death']))"

# The profiling gate (CI-callable): the attribution plane's three
# contracts on the CPU proxy. The reconcile leg serves a seeded mix with
# ServeConfig.attr on and asserts the cost ledger closes: summed
# per-request device-seconds plus warmup device-seconds must equal the
# attribution matrix's serve-phase capacity within max(1 ms, 1%), every
# result verified at the 1e-4 gate, and the roofline series must carry an
# achieved-flops point for every engine the matrix observed. The
# attribution leg forces a synthetic ratchet breach and requires the
# span-tree diff against the best committed prior epoch to NAME the
# guilty phase (headline_slope) — the auto-attribution path bench
# --regress and regress check take on a real failure. The folds leg
# round-trips the recorded stream through folded-stack serialization
# (fold_lines(parse_folded(lines)) == lines) and asserts attr cells
# landed on the stream. The run's s-per-request metrics append to
# reports/history.jsonl (kind: prof, 3 committed epochs) and are
# regress-gated. Timing-gated: honor the serial-ordering note above.
prof-check:
	rm -rf $(PROF_SMOKE) && mkdir -p $(PROF_SMOKE)
	timeout -k 10 420 env JAX_PLATFORMS=cpu $(PYTHON) -m \
	  gauss_tpu.obs.profcheck --seed 258458 --tmpdir $(PROF_SMOKE) \
	  --metrics-out $(PROF_SMOKE)/prof.jsonl \
	  --summary-json $(PROF_SMOKE)/summary.json --regress-check
	$(PYTHON) -c "import json; s=json.load(open('$(PROF_SMOKE)/summary.json')); \
	assert s['invariant_ok'] and s['kind'] == 'prof_check', s; \
	r=s['reconcile']; a=s['attribution']; \
	assert a['named_phase'] == 'headline_slope', a; \
	print('prof-check: reconcile %.6f s vs matrix %.6f s (tol %.6f s); named phase: %s' \
	  % (r['request_device_s'], r['matrix_device_s'], r['tolerance_s'], \
	     a['named_phase']))"

# The replica gate (CI-callable): the network tier's kill-any-replica
# contract. A ≥30-case chaos campaign (SIGKILL mid-load, SIGTERM drain,
# SIGSTOP stall, torn journal tail, expired-during-failover, router
# restarts of the assignment log) plus three live fleet legs: SIGKILL
# each of 3 replicas in turn under load, a budget-free drain, and a
# heartbeat-stall detection — every kill captures a post-mortem bundle
# that passes gauss-debug --check. The invariant is the union journal
# audit: every admitted request reaches exactly ONE terminal across the
# victim+adopter journals (ok results re-verified at the 1e-4 gate from
# journaled operands), zero duplicate solves under resubmission storms
# (exit 2 on any violation). The throughput phase proves horizontal
# scaling: 3 replicas behind the router must clear >= 2x the single-
# replica request rate under an injected per-dispatch delay (nproc-
# independent). replica:s_per_request and replica:failover_recovery_s are
# regress-gated against the committed epochs. Timing-gated: honor the
# serial-ordering note above.
replica-check:
	rm -rf $(REPLICA_SMOKE) && mkdir -p $(REPLICA_SMOKE)
	timeout -k 10 840 env JAX_PLATFORMS=cpu $(PYTHON) -m \
	  gauss_tpu.serve.replicacheck --cases 30 --seed 190733 \
	  --tmpdir $(REPLICA_SMOKE) \
	  --metrics-out $(REPLICA_SMOKE)/replica.jsonl \
	  --summary-json $(REPLICA_SMOKE)/summary.json --regress-check
	$(PYTHON) -m gauss_tpu.obs.summarize $(REPLICA_SMOKE)/replica.jsonl \
	  --json | $(PYTHON) -c "import json,sys; runs=json.load(sys.stdin); \
	rp=[r['replica'] for r in runs.values() if r.get('replica')]; \
	assert rp and rp[0]['campaign'].get('invariant_ok') \
	  and rp[0]['campaign'].get('case_violations') == 0, rp; \
	print('replica-check: campaign summary ok:', rp[0]['campaign'])"

# The poison gate (CI-callable): one bad request must never take down a
# good one. A ≥30-case seeded campaign feeds poison (NaN/Inf operands,
# exactly-singular systems, batch-tripping pills, torn wire payloads)
# next to innocent traffic across in-process servers, a mesh lane, a
# 3-replica router tier, and crash-loop/supervised subprocess legs where
# a journaled admit kills the worker on dispatch. The invariant: every
# innocent is served and re-verified at the 1e-4 gate, every culprit
# draws exactly ONE typed poison terminal (exit 2 on any violation), a
# restart replaying the journal never re-triggers the crash (the blame
# journal quarantines the implicated request), and quarantined deaths
# don't charge the supervisor's restart budget. poison:s_per_case is
# regress-gated against the committed epochs. Timing-gated: honor the
# serial-ordering note above.
poison-check:
	rm -rf $(POISON_SMOKE) && mkdir -p $(POISON_SMOKE)
	timeout -k 10 840 env JAX_PLATFORMS=cpu $(PYTHON) -m \
	  gauss_tpu.serve.poisoncheck --cases 28 --seed 777201 \
	  --tmpdir $(POISON_SMOKE) \
	  --metrics-out $(POISON_SMOKE)/poison.jsonl \
	  --summary-json $(POISON_SMOKE)/summary.json --regress-check
	$(PYTHON) -m gauss_tpu.obs.summarize $(POISON_SMOKE)/poison.jsonl \
	  --json | $(PYTHON) -c "import json,sys; runs=json.load(sys.stdin); \
	po=[r['poison'] for r in runs.values() if r.get('poison')]; \
	assert po and po[0]['campaign'].get('invariant_ok') \
	  and po[0]['campaign'].get('violations') == 0 \
	  and po[0]['campaign'].get('crash_loops') == 0, po; \
	print('poison-check: campaign summary ok:', po[0]['campaign'])"

datasets:
	$(PYTHON) -m gauss_tpu.cli.datasets

clean:
	$(MAKE) -C gauss_tpu/native/src clean
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null || true
