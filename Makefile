# Top-level build/test entry points (reference C9 analog: the reference
# builds each program with documented gcc/nvcc one-liners; here one Makefile
# drives the native library, tests, benchmarks, and dataset regeneration).

PYTHON ?= python

.PHONY: all native test bench datasets clean

all: native

native:
	$(MAKE) -C gauss_tpu/native/src

test: native
	$(PYTHON) -m pytest tests/ -q

bench:
	$(PYTHON) bench.py

datasets:
	$(PYTHON) -m gauss_tpu.cli.datasets

clean:
	$(MAKE) -C gauss_tpu/native/src clean
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null || true
