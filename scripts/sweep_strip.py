"""Sweep GROUP_UPDATE_STRIP for the deferred-update chunked factorization.

The strip loop bounds group-end transients to O(strip * n) so n=32768 fits
HBM (core/blocked.py GROUP_UPDATE_STRIP); but at moderate n the stripping
serializes the one deferred trailing GEMM into several gather+GEMM rounds
that a single unstripped pass may beat. This sweeps the strip size on the
chip to find the routing rule.

Monkeypatches the module constant; jax.clear_caches() between configs is
REQUIRED because the constant is read at trace time and is not part of the
jit cache key.

Usage: python scripts/sweep_strip.py <n> <strip> [<strip> ...]
       (strip 0 means unstripped: strip = full trailing height)
"""
import sys
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from gauss_tpu.bench.slope import measure_slope_info, solver_chain
from gauss_tpu.core import blocked

n = int(sys.argv[1])
strips = [int(v) for v in sys.argv[2:]]
rng = np.random.default_rng(0)
a = rng.standard_normal((n, n)).astype(np.float32)
a[np.arange(n), np.arange(n)] += n / 100.0
b = rng.standard_normal(n).astype(np.float32)
ad = jax.block_until_ready(jnp.asarray(a))
bd = jax.block_until_ready(jnp.asarray(b))

for strip in strips:
    jax.clear_caches()
    blocked.GROUP_UPDATE_STRIP = strip if strip else 1 << 30
    # Force the explicit strip to be honored: below the unstripped gate the
    # factorization would ignore GROUP_UPDATE_STRIP and every config would
    # time the same single-pass program. strip 0 sweeps the unstripped form
    # explicitly, so the gate value is irrelevant there.
    blocked.GROUP_UPDATE_UNSTRIPPED_MAX_BYTES = 1 << 62 if not strip else 0

    factor = blocked.resolve_factor(n, "auto")
    # Guard against a silent no-op: GROUP_UPDATE_STRIP is read only by the
    # chunked factorization; auto resolves elsewhere for n <= UNROLL_MAX_N,
    # non-TPU backends, and past MAX_CHUNK's reach.
    resolved = factor.func if isinstance(factor, partial) else factor
    if resolved is not blocked.lu_factor_blocked_chunked:
        sys.exit(f"n={n} resolves to {resolved.__name__}, which ignores "
                 "GROUP_UPDATE_STRIP; pick n that routes chunked on this "
                 "backend")

    def solve_once(a_, b_):
        # panel=None resolves through auto_panel(n), matching every
        # production call site (the function default is NOT the auto panel).
        return blocked.lu_solve(factor(a_, panel=None), b_)

    x = np.asarray(solve_once(ad, bd), np.float64)
    r = np.linalg.norm(a @ x - b) / np.linalg.norm(b)
    make_chain, args = solver_chain(ad, bd, solve_once)
    sec, k1, k2, is_slope = measure_slope_info(make_chain, args,
                                               k_small=1, k_large=4,
                                               rounds=8)
    print(f"n={n} strip={strip or 'full'}: {sec*1e3:.1f} ms "
          f"(K={k1}/{k2}, slope={is_slope}, relres={r:.1e})", flush=True)
