"""One-shot timing of the FLAT fori factorization route (comparison for
the chunked route at sizes near the HBM ceiling).

Usage: python scripts/bench_flat.py [n] [reps]
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from gauss_tpu.bench.slope import gauss_solve_once
from gauss_tpu.core.blocked import auto_panel

n = int(sys.argv[1]) if len(sys.argv) > 1 else 32768
reps = int(sys.argv[2]) if len(sys.argv) > 2 else 2
rng = np.random.default_rng(0)
a = rng.standard_normal((n, n)).astype(np.float32)
a[np.arange(n), np.arange(n)] += n / 100.0
b = rng.standard_normal(n).astype(np.float32)
ad = jax.block_until_ready(jnp.asarray(a))
bd = jax.block_until_ready(jnp.asarray(b))
panel = auto_panel(n)
print(f"n={n}: flat route (unroll=False), panel={panel}", flush=True)
t0 = time.perf_counter()
x = np.asarray(gauss_solve_once(ad, bd, panel, unroll=False), np.float64)
print(f"compile+first: {time.perf_counter()-t0:.1f} s", flush=True)
r = np.linalg.norm(a @ x - b) / np.linalg.norm(b)
print(f"relres={r:.1e}", flush=True)
ts = []
for _ in range(reps):
    t0 = time.perf_counter()
    np.asarray(gauss_solve_once(ad, bd, panel, unroll=False))
    ts.append(time.perf_counter() - t0)
print(f"n={n} flat: {min(ts):.3f} s one-shot min of {reps} "
      f"(all={[f'{t:.2f}' for t in ts]})", flush=True)
