"""Sweep (panel, chunk) for the grouped chunked factorization on the chip.

Usage: python scripts/sweep_grouped.py <n> "panel,chunk" "panel,chunk" ...
"""
import sys
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from gauss_tpu.bench.slope import measure_slope_info, solver_chain
from gauss_tpu.core import blocked

n = int(sys.argv[1])
configs = [tuple(int(v) for v in s.split(",")) for s in sys.argv[2:]]
rng = np.random.default_rng(0)
a = rng.standard_normal((n, n)).astype(np.float32)
a[np.arange(n), np.arange(n)] += n / 100.0
b = rng.standard_normal(n).astype(np.float32)
ad = jax.block_until_ready(jnp.asarray(a))
bd = jax.block_until_ready(jnp.asarray(b))

for panel, chunk in configs:
    def solve_once(a_, b_, panel=panel, chunk=chunk):
        fac = blocked.lu_factor_blocked_chunked(a_, panel=panel, chunk=chunk)
        return blocked.lu_solve(fac, b_)

    x = np.asarray(solve_once(ad, bd), np.float64)
    r = np.linalg.norm(a @ x - b) / np.linalg.norm(b)
    make_chain, args = solver_chain(ad, bd, solve_once)
    sec, k1, k2, is_slope = measure_slope_info(make_chain, args,
                                               k_small=1, k_large=4,
                                               rounds=8)
    print(f"n={n} panel={panel} chunk={chunk}: {sec*1e3:.1f} ms "
          f"(K={k1}/{k2}, slope={is_slope}, relres={r:.1e})", flush=True)
