"""Time each component of the chunked factorization's per-group epilogue.

The n=8192 factor runs ~43 ms against ~14 ms of GEMM-bound work and ~12 ms
of panel chain (scripts/decompose_8192.py), leaving ~16 ms in the group
epilogue: permutation gathers, the U12 block substitution scan, and the
strip-looped trailing GEMM. This times each component standalone at the
REAL per-group shapes (summed over groups) so the glue budget has names,
and times drop-in alternatives next to the shipped forms:

- u12-scan vs u12 via a composed group L-inverse (one GEMM);
- strip-looped trailing update vs one unstripped gather + GEMM.

Usage: python scripts/decompose_group.py [n [panel [chunk]]]
"""
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

sys.path.insert(0, ".")

from gauss_tpu.bench.slope import measure_slope_info
from gauss_tpu.core.blocked import GROUP_UPDATE_STRIP

n = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
panel = int(sys.argv[2]) if len(sys.argv) > 2 else 256
chunk = int(sys.argv[3]) if len(sys.argv) > 3 else 4
prec = lax.Precision.HIGHEST
nb = n // panel
w = chunk * panel
rng = np.random.default_rng(0)
m_host = rng.standard_normal((n, n)).astype(np.float32)
md = jax.block_until_ready(jnp.asarray(m_host))
# A realistic group permutation: the factorization's gperm for the group at
# gs is a permutation of the LOCAL trailing range, so every per-group slice
# below must yield in-range local indices (a global shuffle would go
# negative after the -gs shift and silently clamp in the gather). Shuffling
# each group-width segment locally keeps all slices valid and every timed
# group genuinely permuted.
perm_host = np.arange(n)
for s0 in range(0, n, chunk * panel):
    rng.shuffle(perm_host[s0:s0 + chunk * panel])  # in-place via the view
permd = jax.block_until_ready(jnp.asarray(perm_host))

groups = [(g0 * panel, n - g0 * panel) for g0 in range(0, nb, chunk)]


def timed(name, make_chain, args, ks=4, kl=16):
    sec, k1, k2, s = measure_slope_info(make_chain, args, k_small=ks,
                                        k_large=kl, rounds=6)
    print(f"{name}: {sec*1e3:.2f} ms (K={k1}/{k2}, slope={s})", flush=True)
    return sec


def chain(body):
    """Wrap a per-iteration body(m, perm, x) -> scalar into a K-chain."""

    def make_chain(k):
        @jax.jit
        def run(m_, perm_, x0):
            def step(_, x):
                return body(m_, perm_, x)

            return lax.fori_loop(0, k, step, x0)

        return run

    return make_chain


zero = jnp.zeros((), jnp.float32)


def _jitter(acc):
    """A carry-dependent int32 zero XLA cannot fold away: an int `x * 0`
    simplifies to a constant and the gathers become loop-invariant
    (hoistable out of the K-chain); a float scale then cast stays dynamic."""
    return (acc * jnp.float32(1e-30)).astype(jnp.int32)

# 1. top gather: (w, rt) permuted block-row read, summed over groups.


def top_gather(m_, perm_, x):
    acc = x
    for gs, gh in groups:
        rt = gh - w
        if rt <= 0:
            continue
        gp = lax.dynamic_slice(perm_, (gs,), (w,)) - gs + _jitter(x)
        top = m_[gs + gp][:, gs + w:]
        acc = acc + top[0, 0]
    return acc


t_top = timed("top gathers (all groups)", chain(top_gather), (md, permd, zero))

# 2. u12 scan (shipped form) vs one composed-Linv GEMM.
linvs = jax.block_until_ready(
    jnp.asarray(rng.standard_normal((chunk, panel, panel)), jnp.float32))


def u12_scan(m_, perm_, x):
    acc = x
    for gs, gh in groups:
        rt = gh - w
        if rt <= 0:
            continue
        grp = lax.dynamic_slice(m_, (gs, gs), (gh, w))
        top = lax.dynamic_slice(m_, (gs, gs + w), (w, rt)) + acc

        def usolve(xc, i, grp=grp, top=top, rt=rt):
            rows = lax.dynamic_slice(grp, (i * panel, 0), (panel, w))
            r = lax.dynamic_slice(top, (i * panel, 0), (panel, rt))
            r = r - jnp.dot(rows, xc, precision=prec)
            xi = jnp.dot(linvs[i], r, precision=prec)
            return lax.dynamic_update_slice(xc, xi, (i * panel, 0)), i

        u12, _ = lax.scan(usolve, jnp.zeros((w, rt), jnp.float32),
                          jnp.arange(chunk))
        acc = acc + u12[0, 0]
    return acc


t_scan = timed("u12 scan (all groups)", chain(u12_scan), (md, permd, zero))


def u12_inverse(m_, perm_, x):
    acc = x
    for gs, gh in groups:
        rt = gh - w
        if rt <= 0:
            continue
        grp = lax.dynamic_slice(m_, (gs, gs), (gh, w))
        top = lax.dynamic_slice(m_, (gs, gs + w), (w, rt)) + acc
        # Compose Linv_group (w x w) blockwise from panel inverses:
        # row block i: Linv[i, j] = -linvs[i] @ L[i, j] @ Linv[j, :] built
        # progressively; cost O(chunk^2) panel-size GEMMs per group.
        rowsL = [[None] * chunk for _ in range(chunk)]
        for i in range(chunk):
            for j in range(i):
                s = jnp.zeros((panel, panel), jnp.float32)
                for k in range(j, i):
                    lik = lax.dynamic_slice(grp, (i * panel, k * panel),
                                            (panel, panel))
                    s = s + jnp.dot(lik, rowsL[k][j], precision=prec)
                rowsL[i][j] = -jnp.dot(linvs[i], s, precision=prec)
            rowsL[i][i] = linvs[i]
        linv_g = jnp.concatenate(
            [jnp.concatenate(
                [rowsL[i][j] if j <= i else jnp.zeros((panel, panel),
                                                      jnp.float32)
                 for j in range(chunk)], axis=1)
             for i in range(chunk)], axis=0)
        u12 = jnp.dot(linv_g, top, precision=prec)
        acc = acc + u12[0, 0]
    return acc


t_inv = timed("u12 composed-Linv GEMM (all groups)", chain(u12_inverse),
              (md, permd, zero))

# 3. trailing update: strip loop (shipped) vs unstripped single pass.


def trailing(strip):
    def body(m_, perm_, x):
        acc = x
        for gs, gh in groups:
            rt = gh - w
            if rt <= 0:
                continue
            grp = lax.dynamic_slice(m_, (gs, gs), (gh, w))
            u12 = lax.dynamic_slice(m_, (gs, gs + w), (w, rt)) + acc
            sw = min(strip, gh - w)
            nfull = (gh - w) // sw
            fresh = jnp.zeros((gh - w, rt), jnp.float32)

            # acc-dependence keeps the gathers loop-variant across the
            # K-chain (otherwise XLA's LICM could hoist them and the chain
            # would time only the dots).
            jitter = _jitter(acc)

            def strip_body(s, fresh, gs=gs, gh=gh, rt=rt, sw=sw, grp=grp,
                           u12=u12, jitter=jitter):
                r0 = w + s * sw
                idx = lax.dynamic_slice(perm_, (gs + r0,), (sw,)) - gs + jitter
                old = m_[gs + idx][:, gs + w:]
                l21 = lax.dynamic_slice(grp, (r0, 0), (sw, w))
                return lax.dynamic_update_slice(
                    fresh, old - jnp.dot(l21, u12, precision=prec),
                    (s * sw, 0))

            fresh = lax.fori_loop(0, nfull, strip_body, fresh)
            tail = (gh - w) - nfull * sw
            if tail:
                idx = perm_[gs + w + nfull * sw:gs + gh] - gs + jitter
                old = m_[gs + idx][:, gs + w:]
                l21 = grp[w + nfull * sw:]
                fresh = lax.dynamic_update_slice(
                    fresh, old - jnp.dot(l21, u12, precision=prec),
                    (nfull * sw, 0))
            acc = acc + fresh[0, 0]
        return acc

    return body


t_strip = timed(f"trailing strip={GROUP_UPDATE_STRIP} (all groups)",
                chain(trailing(GROUP_UPDATE_STRIP)), (md, permd, zero),
                ks=1, kl=4)
t_full = timed("trailing unstripped (all groups)",
               chain(trailing(1 << 30)), (md, permd, zero), ks=1, kl=4)

# 4. left realign gather: m[gs:, :gs][gperm] summed over groups.


def left_realign(m_, perm_, x):
    acc = x
    for gs, gh in groups:
        if not gs:
            continue
        gp = lax.dynamic_slice(perm_, (gs,), (gh,)) - gs + _jitter(acc)
        left = m_[gs:][gp][:, :gs]
        acc = acc + left[0, 0]
    return acc


t_left = timed("left realign gathers (all groups)", chain(left_realign),
               (md, permd, zero))

print(f"\nepilogue accounted: top {t_top*1e3:.1f} + u12-scan "
      f"{t_scan*1e3:.1f} + trailing-strip {t_strip*1e3:.1f} + left "
      f"{t_left*1e3:.1f} = "
      f"{(t_top + t_scan + t_strip + t_left)*1e3:.1f} ms", flush=True)
print(f"alternatives: u12-inv {t_inv*1e3:.1f} ms, trailing-unstripped "
      f"{t_full*1e3:.1f} ms", flush=True)
