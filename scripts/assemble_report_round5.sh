#!/usr/bin/env bash
# Assemble reports/REPORT.md + graphs/ from the round-5 regenerated cells
# (/tmp/r5_*.json) plus the round-3 cells that remain current:
#   - cells_precision.json      (MXU precision sweep; code path unchanged)
#   - cells_gauss_dist.json     (virtual-mesh shard sweep n=128..2048)
#   - cells_gauss_dist_4096.json (round-4 extension, blocked engines)
#   - cells_gauss_internal_threads.json / _4096_native.json (native thread
#     sweep; native engines unchanged)
# Run AFTER scripts/regen_round5.sh reports all stages done; copies the
# fresh cells into reports/ under their round-3 names so the committed
# artifact set stays stable.
set -euo pipefail
cd "$(dirname "$0")/.."

declare -A dest=(
    [gi]=cells_gauss_internal.json
    [gid]=cells_gauss_internal_device.json
    [gil]=cells_gauss_internal_large.json
    [gi16]=cells_gauss_internal_16384.json
    [gi32]=cells_gauss_internal_32768.json
    [mm24]=cells_matmul_24576.json
    [ge]=cells_gauss_external.json
    [gem]=cells_gauss_external_memplus.json
    [gemd]=cells_gauss_external_memplus_dev.json
    [ged]=cells_gauss_external_device.json
    [mm]=cells_matmul.json
    [mmd]=cells_matmul_device.json
    [mm16]=cells_matmul_16384.json
    [mm48]=cells_matmul_4096_8192.json
)
missing=0
for k in "${!dest[@]}"; do
    if [ -s "/tmp/r5_$k.json" ]; then
        cp "/tmp/r5_$k.json" "reports/${dest[$k]}"
    else
        echo "MISSING /tmp/r5_$k.json (keeping old reports/${dest[$k]} if present)"
        missing=$((missing+1))
    fi
done
# Old per-size matmul files are superseded by cells_matmul_4096_8192.json.
[ -s reports/cells_matmul_4096_8192.json ] && rm -f reports/cells_matmul_4096.json reports/cells_matmul_8192.json

files=(reports/cells_gauss_internal.json reports/cells_gauss_internal_device.json
       reports/cells_gauss_internal_large.json reports/cells_gauss_internal_16384.json
       reports/cells_gauss_internal_threads.json reports/cells_gauss_internal_4096_native.json
       reports/cells_gauss_external.json reports/cells_gauss_external_memplus.json
       reports/cells_gauss_external_memplus_dev.json reports/cells_gauss_external_device.json
       reports/cells_matmul.json reports/cells_matmul_device.json)
[ -s reports/cells_matmul_16384.json ] && files+=(reports/cells_matmul_16384.json)
[ -s reports/cells_gauss_internal_32768.json ] && files+=(reports/cells_gauss_internal_32768.json)
[ -s reports/cells_matmul_24576.json ] && files+=(reports/cells_matmul_24576.json)
if [ -s reports/cells_matmul_4096_8192.json ]; then
    files+=(reports/cells_matmul_4096_8192.json)
else
    # mm48 stage missing: keep the round-3 per-size cells so the 4096/8192
    # matmul rows never silently vanish from the report.
    [ -s reports/cells_matmul_4096.json ] && files+=(reports/cells_matmul_4096.json)
    [ -s reports/cells_matmul_8192.json ] && files+=(reports/cells_matmul_8192.json)
fi
files+=(reports/cells_precision.json reports/cells_gauss_dist.json reports/cells_gauss_dist_4096.json)
# Round-5: all four dist engines run on the REAL chip as a 1-device mesh
# (lowering + verification proof; --dist-device default).
[ -s reports/cells_gauss_dist_tpu1.json ] && files+=(reports/cells_gauss_dist_tpu1.json)

python -m gauss_tpu.bench.report "${files[@]}" \
    --title "gauss-tpu benchmark report (round 5)" --out reports/REPORT.md --profile 1024
python -m gauss_tpu.bench.plots reports/cells_gauss_internal.json \
    reports/cells_gauss_internal_device.json reports/cells_matmul_device.json \
    --outdir graphs
echo "REPORT.md + graphs regenerated (missing stages: $missing)"
