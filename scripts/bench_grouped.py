"""Quick device-span timing of the restructured chunked factorization.

Usage: PYTHONPATH=. python scripts/bench_grouped.py [n ...]
Slope-timed (bench.slope) factor+solve on the real chip via the auto route.
"""
import sys

import jax
import jax.numpy as jnp
import numpy as np

from gauss_tpu.bench.slope import gauss_chain, measure_slope_info
from gauss_tpu.core.blocked import auto_panel, resolve_factor
from gauss_tpu.bench.slope import gauss_solve_once

sizes = [int(s) for s in sys.argv[1:]] or [8192, 16384]
ROUNDS = int(__import__("os").environ.get("BG_ROUNDS", "5"))
rng = np.random.default_rng(0)

for n in sizes:
    f = resolve_factor(n, "auto")
    kw = getattr(f, "keywords", {})
    name = getattr(f, "func", f).__name__
    panel = auto_panel(n)
    print(f"n={n}: route={name} {kw} panel={panel}", flush=True)
    a = rng.standard_normal((n, n)).astype(np.float32)
    a[np.arange(n), np.arange(n)] += n / 100.0
    b = rng.standard_normal(n).astype(np.float32)
    ad = jax.block_until_ready(jnp.asarray(a))
    bd = jax.block_until_ready(jnp.asarray(b))
    # Verify the exact measured configuration once.
    x = np.asarray(gauss_solve_once(ad, bd, panel), np.float64)
    r = np.linalg.norm(a @ x - b) / np.linalg.norm(b)
    print(f"n={n}: relres={r:.2e}", flush=True)
    if n >= 28000:
        # Chains hold an extra perturbed matrix copy (HBM-prohibitive near
        # the ceiling); per-solve seconds dwarf the ~0.1 s dispatch offset,
        # so one-shot fetch-bounded wall-clock is honest here.
        import time
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(gauss_solve_once(ad, bd, panel))
            ts.append(time.perf_counter() - t0)
        print(f"n={n}: {min(ts):.3f} s per factor+solve (one-shot min of 3, "
              f"all={[f'{t:.2f}' for t in ts]})", flush=True)
        continue
    ks, kl, rounds = (1, 4, ROUNDS) if n >= 8192 else (4, 16, ROUNDS)
    make_chain, args = gauss_chain(ad, bd, panel)
    sec, k1, k2, is_slope = measure_slope_info(make_chain, args,
                                               k_small=ks, k_large=kl,
                                               rounds=rounds)
    print(f"n={n}: {sec*1e3:.1f} ms per factor+solve "
          f"(K={k1}/{k2}, slope={is_slope})", flush=True)
