"""Sweep Pallas matmul tile shapes (bf16x3 in-kernel) vs the XLA engine.

Usage: python scripts/sweep_matmul.py <n> "bm,bn,bk" ... (no configs = defaults)
"""
import sys
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from gauss_tpu.bench.slope import matmul_chain, measure_slope_info
from gauss_tpu.core.matmul import matmul
from gauss_tpu.kernels.matmul_pallas import matmul_pallas

n = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
configs = [tuple(int(v) for v in s.split(",")) for s in sys.argv[2:]] or [
    (256, 256, 512), (256, 512, 512), (512, 256, 512), (256, 256, 1024),
    (512, 512, 512), (128, 512, 512)]
rng = np.random.default_rng(0)
a = jax.block_until_ready(jnp.asarray(
    rng.standard_normal((n, n)).astype(np.float32)))
b = jax.block_until_ready(jnp.asarray(
    rng.standard_normal((n, n)).astype(np.float32)))


def bench(name, mm):
    mk, args = matmul_chain(a, b, mm)
    sec, k1, k2, s = measure_slope_info(mk, args)
    gf = 2 * n**3 / sec / 1e9
    print(f"{name}: {sec*1e3:.3f} ms ({gf/1000:.1f} TF/s, K={k1}/{k2}, "
          f"slope={s})", flush=True)
    return sec


t_xla = bench("xla high (bf16x3)", matmul)
for bm, bn, bk in configs:
    t = bench(f"pallas bf16x3 bm={bm} bn={bn} bk={bk}",
              partial(matmul_pallas, bm=bm, bn=bn, bk=bk))
    print(f"   -> {t/t_xla:.2f}x of XLA", flush=True)
