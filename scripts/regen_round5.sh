#!/usr/bin/env bash
# Round-5 report regeneration, staged so partial results survive
# interruption (same shape as regen_round4.sh, which was never fully
# adopted — VERDICT r4 missing #1). Run on the TPU host; takes a few hours
# behind the tunnel. Stages write /tmp/r5_*.json; adopt with
# scripts/assemble_report_round5.sh when all stages are done.
#
# Every device-span gauss cell exercises the round-5 two-level (deferred)
# panel kernel, so ALL stages regenerate — no round-4 cells are current.
set -uo pipefail
cd "$(dirname "$0")/.."

stage() {  # stage <name> <args...>: skip if the json already exists
    local out="/tmp/r5_$1.json"; shift
    if [ -s "$out" ]; then echo "== skip $out (exists)"; return 0; fi
    echo "== running $out ($(date +%H:%M:%S))"
    python -m gauss_tpu.bench.grid "$@" --json "$out" || echo "== FAILED $out"
}

stage gid  --suite gauss-internal \
           --backends tpu,tpu-rowelim,tpu-rowelim-step,jax-linalg --span device
stage mmd  --suite matmul --backends tpu,tpu-pallas,tpu-pallas-v1,tpu-dist \
           --span device
stage mm48 --suite matmul --keys 4096,8192 --backends tpu,tpu-pallas \
           --span device
stage gi   --suite gauss-internal \
           --backends tpu,tpu-unblocked,seq,omp,threads,forkjoin,tiled
stage gil  --suite gauss-internal --keys 4096,8192 \
           --backends tpu,tpu-rowelim,jax-linalg --span device
stage gi16 --suite gauss-internal --keys 16384 \
           --backends tpu,tpu-rowelim,jax-linalg --span device
# The 24.5k-34k band: the chunk-escalated deferred-update route must beat
# the flat fori fallback all the way to the HBM ceiling — these are the
# REPORT cells that back the README/DESIGN claims (VERDICT r4 missing #1).
stage gi32 --suite gauss-internal --keys 24576,32768 \
           --backends tpu --span device
stage ge   --suite gauss-external --backends tpu,seq,omp \
           --keys matrix_10,jpwh_991,orsreg_1,sherman5,saylr4,sherman3
stage ged  --suite gauss-external --backends tpu --span device
stage mm   --suite matmul --backends tpu,tpu-pallas,tpu-pallas-v1,seq,omp
stage mm16 --suite matmul --keys 16384 --backends tpu,tpu-pallas --span device
stage mm24 --suite matmul --keys 24576 --backends tpu --span device
# memplus last: its ds-chain compile at n=17758 is the longest pole and has
# hung behind a dropped tunnel once; isolated so the rest of the grid lands.
stage gem  --suite gauss-external --keys memplus --backends tpu
stage gemd --suite gauss-external --keys memplus --backends tpu --span device

echo "== all stages done ($(date +%H:%M:%S)); artifacts in /tmp/r5_*.json"
