"""Decompose the UNROLLED route's factor+solve at the headline size.

The n=2048 headline runs lu_factor_blocked_unrolled (panel=256, nb=8,
Pallas panel kernel) + blockwise lu_solve and delivers ~2.1 ms against a
~0.22 ms 2/3*n^3 roofline at HIGHEST-precision GEMM rate (VERDICT r4
weak #5). This times each per-panel component standalone at the TRUE
shrinking shapes, summed over panels, so the ~10x gap has names:

  1. panel chain: nb panel_factor_pallas calls on (tail, panel) strips
  2. full-width row gathers m[kb:][perm_local] (the pivot permutation)
  3. diagonal-block TRTRI pairs (unit_lower_inv + upper_inv)
  4. u12 + trailing GEMMs at HIGHEST (6-pass) and "high" (bf16x3)
  5. solve only (blockwise TRTRI+GEMM substitution)

Usage: python scripts/decompose_unrolled.py [n [panel]]
"""
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

sys.path.insert(0, ".")

from gauss_tpu.bench.slope import PERTURB, measure_slope_info
from gauss_tpu.core import blocked
from gauss_tpu.kernels.panel_pallas import panel_factor_pallas

n = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
panel = int(sys.argv[2]) if len(sys.argv) > 2 else 256
nb = n // panel
rng = np.random.default_rng(0)
a = rng.standard_normal((n, n)).astype(np.float32)
a[np.arange(n), np.arange(n)] += n / 100.0
b = rng.standard_normal(n).astype(np.float32)
ad = jax.block_until_ready(jnp.asarray(a))
bd = jax.block_until_ready(jnp.asarray(b))
zero = jnp.zeros((), jnp.float32)


def report(name, make_chain, args, ks=4, kl=16):
    sec, k1, k2, s = measure_slope_info(make_chain, args, k_small=ks,
                                        k_large=kl, rounds=6)
    print(f"{name}: {sec*1e3:.3f} ms (K={k1}/{k2}, slope={s})", flush=True)
    return sec


def chain(body):
    def make_chain(k):
        @jax.jit
        def run(a_, x0):
            return lax.fori_loop(0, k, lambda _, x: body(a_, x), x0)

        return run

    return make_chain


def _jitter(acc):
    """Carry-dependent int32 zero XLA cannot constant-fold (keeps gathers
    loop-variant across the K-chain; see decompose_group)."""
    return (acc * jnp.float32(1e-30)).astype(jnp.int32)


# 0. Whole op: factor + solve exactly as the headline runs it.
def whole(a_, x):
    fac = blocked.lu_factor_blocked_unrolled(
        a_ + x * jnp.asarray(PERTURB, a_.dtype), panel=panel)
    return blocked.lu_solve(fac, bd)[0]


t_all = report("factor+solve (headline op)", chain(whole), (ad, zero))


# 0b. Factor only.
def factor_only(a_, x):
    fac = blocked.lu_factor_blocked_unrolled(
        a_ + x * jnp.asarray(PERTURB, a_.dtype), panel=panel)
    return fac.m[0, 0] + fac.min_abs_pivot


t_factor = report("factor only", chain(factor_only), (ad, zero))


# 0c. Solve only (factor fixed, chained perturbed solves).
fac0 = jax.block_until_ready(
    blocked.lu_factor_blocked_unrolled(ad, panel=panel))


def make_solve_chain(k):
    @jax.jit
    def run(m, perm, mp, linv, uinv, b_, x0):
        f = blocked.BlockedLU(m, perm, mp, linv, uinv)

        def body(_, x):
            return blocked.lu_solve(f, b_ + x[0] * jnp.asarray(PERTURB,
                                                               b_.dtype))

        return jnp.sum(lax.fori_loop(0, k, body, x0))

    return run


t_solve = report("solve only", make_solve_chain,
                 (fac0.m, fac0.perm, fac0.min_abs_pivot, fac0.linv,
                  fac0.uinv, bd, bd))


# 1. Panel chain at the true shrinking shapes.
def panels(a_, x):
    acc = x
    for kb in range(0, n, panel):
        p = lax.dynamic_slice(a_, (kb, kb), (n - kb, panel)) \
            + acc * jnp.asarray(PERTURB, a_.dtype)
        out, ipiv, perm_local, mp = panel_factor_pallas(p, 0)
        acc = acc + out[0, 0] + mp
    return acc


t_panels = report(f"panel chain ({nb} true-shape kernels)", chain(panels),
                  (ad, zero))


# 2. Full-width gathers at the true shapes.
perm_host = np.arange(n)
for kb in range(0, n, panel):
    rng.shuffle(perm_host[kb:kb + panel])
permd = jax.block_until_ready(jnp.asarray(perm_host))


def gathers(a_, x):
    acc = x
    for kb in range(0, n, panel):
        pl = lax.dynamic_slice(permd, (kb,), (n - kb,)) - kb + _jitter(acc)
        live = a_[kb:][pl]
        acc = acc + live[0, 0]
    return acc


t_gather = report(f"row gathers ({nb} full-width)", chain(gathers),
                  (ad, zero))


# 3. Diagonal-block inverse pairs.
def invs(a_, x):
    acc = x
    for kb in range(0, n, panel):
        d = lax.dynamic_slice(a_, (kb, kb), (panel, panel)) \
            + acc * jnp.asarray(PERTURB, a_.dtype)
        linv, uinv = blocked._diag_block_invs(d, panel, jnp.float32)
        acc = acc + linv[0, 0] + uinv[0, 0]
    return acc


t_invs = report(f"diag-block TRTRI pairs ({nb})", chain(invs), (ad, zero))


# 4. u12 + trailing GEMMs at the true shapes, both precisions.
def gemms(prec):
    def body(a_, x):
        acc = x
        for kb in range(0, n - panel, panel):
            tail = n - kb
            live = lax.dynamic_slice(a_, (kb, kb), (tail, tail)) \
                + acc * jnp.asarray(PERTURB, a_.dtype)
            linv = lax.dynamic_slice(a_, (0, 0), (panel, panel))
            u12 = jnp.dot(linv, live[:panel, panel:], precision=prec)
            l21 = live[panel:, :panel]
            upd = live[panel:, panel:] - jnp.dot(l21, u12, precision=prec)
            acc = acc + upd[0, 0]
        return acc

    return body


t_gemm_hi = report("u12+trailing GEMMs (HIGHEST)",
                   chain(gemms(lax.Precision.HIGHEST)), (ad, zero))
t_gemm_bf = report("u12+trailing GEMMs (DEFAULT single-pass)",
                   chain(gemms(lax.Precision.DEFAULT)), (ad, zero))

print(f"\nfactor accounted: panels {t_panels*1e3:.2f} + gathers "
      f"{t_gather*1e3:.2f} + invs {t_invs*1e3:.2f} + gemms(HIGHEST) "
      f"{t_gemm_hi*1e3:.2f} = "
      f"{(t_panels + t_gather + t_invs + t_gemm_hi)*1e3:.2f} ms "
      f"(measured factor {t_factor*1e3:.2f} ms)", flush=True)
print(f"whole: {t_all*1e3:.2f} ms = factor {t_factor*1e3:.2f} + solve "
      f"{t_solve*1e3:.2f}; GEMM default-pass alternative "
      f"{t_gemm_bf*1e3:.2f} ms", flush=True)
