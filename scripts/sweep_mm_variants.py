"""Close the last 4% between the hand Pallas matmul and XLA's engine.

Both are MXU-bound at 3-pass bf16x3 "high" (n=8192: ~16.7 ms theoretical,
XLA 17.55, ours 18.25 after the round-4 tile sweep — VERDICT r4 weak #4),
so the gap is pipeline efficiency, not traffic. Variants tried here:

  xla        jnp.dot precision=HIGH (the engine to beat)
  base       shipped matmul_pallas (in-kernel bf16 split per tile visit)
  semantics  + dimension_semantics=(parallel, parallel, arbitrary)
  presplit   operands split hi/lo ONCE at the XLA level, kernel takes 4
             bf16 inputs and runs 3 dots with no per-tile VPU split work
  presplit+s presplit + dimension_semantics

Usage: python scripts/sweep_mm_variants.py [n]
(n must be a multiple of 1024: these experimental variants tile without
padding, unlike the shipped matmul_pallas.)
"""
import sys
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")

from gauss_tpu.bench.slope import measure_slope_info
from gauss_tpu.bench.slope import matmul_chain
from gauss_tpu.kernels.matmul_pallas import matmul_pallas

n = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
if n % 1024:
    sys.exit(f"n={n} must be a multiple of 1024 (no padding in these "
             f"experimental variants)")
rng = np.random.default_rng(0)
a = rng.standard_normal((n, n)).astype(np.float32)
b = rng.standard_normal((n, n)).astype(np.float32)
ad = jax.block_until_ready(jnp.asarray(a))
bd = jax.block_until_ready(jnp.asarray(b))


def _split_kernel(ah_ref, al_ref, bh_ref, bl_ref, o_ref, acc_ref):
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc = acc_ref.dtype
    acc_ref[:] += (jnp.dot(ah_ref[:], bl_ref[:], preferred_element_type=acc)
                   + jnp.dot(al_ref[:], bh_ref[:], preferred_element_type=acc)
                   + jnp.dot(ah_ref[:], bh_ref[:], preferred_element_type=acc))

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _store():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


@partial(jax.jit, static_argnames=("bm", "bn", "bk", "semantics"))
def matmul_presplit(a, b, bm=512, bn=512, bk=1024, semantics=False):
    m, k = a.shape
    _, nn = b.shape
    a_hi = a.astype(jnp.bfloat16)
    a_lo = (a - a_hi.astype(a.dtype)).astype(jnp.bfloat16)
    b_hi = b.astype(jnp.bfloat16)
    b_lo = (b - b_hi.astype(b.dtype)).astype(jnp.bfloat16)
    grid = (m // bm, nn // bn, k // bk)
    params = {}
    if semantics:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    return pl.pallas_call(
        _split_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, nn), a.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        **params,
    )(a_hi, a_lo, b_hi, b_lo)


@partial(jax.jit, static_argnames=("bm", "bn", "bk"))
def matmul_semantics(a, b, bm=512, bn=512, bk=1024):
    """Shipped kernel body + dimension_semantics, via a local pallas_call."""
    from gauss_tpu.kernels.matmul_pallas import _mm_kernel

    m, k = a.shape
    _, nn = b.shape
    return pl.pallas_call(
        partial(_mm_kernel, precision=None, k_axis=2, bf16x3=True),
        grid=(m // bm, nn // bn, k // bk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, nn), a.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(a, b)


def timed(name, mm):
    make_chain, args = matmul_chain(ad, bd, mm)
    sec, k1, k2, s = measure_slope_info(make_chain, args, k_small=1,
                                        k_large=4, rounds=6)
    print(f"{name}: {sec*1e3:.2f} ms (K={k1}/{k2}, slope={s})", flush=True)
    return sec


ref64 = None
if n <= 2048:
    ref64 = a.astype(np.float64) @ b.astype(np.float64)
    for nm, mm in (("presplit", matmul_presplit), ("semantics", matmul_semantics)):
        c = np.asarray(mm(ad, bd))
        err = np.abs(c - ref64).max() / np.abs(ref64).max()
        print(f"{nm} max rel err: {err:.2e}")

timed("xla HIGH", lambda x, y: jnp.dot(x, y, precision=lax.Precision.HIGH))
timed("base", lambda x, y: matmul_pallas(x, y))
timed("semantics", matmul_semantics)
timed("presplit", matmul_presplit)
timed("presplit+sem", partial(matmul_presplit, semantics=True))
