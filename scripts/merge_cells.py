"""Splice re-measured bench cells into an existing cells JSON.

The tunneled chip occasionally lands a jitter-contaminated cell despite the
slope method's hardening (e.g. a small-n cell 20x its neighbors). The fix
is to re-measure just that cell with the same grid CLI and replace it:

    python -m gauss_tpu.bench.grid --suite gauss-internal --keys 256 \
        --backends tpu --span device --json /tmp/fix.json
    python scripts/merge_cells.py /tmp/r4_gid.json /tmp/fix.json

Cells are keyed by (suite, key, backend, span); the patch file wins. The
target is rewritten in place (a .bak copy is left beside it).
"""
import json
import os
import shutil
import sys

if len(sys.argv) < 3:
    sys.exit(f"usage: {sys.argv[0]} <target.json> <patch.json> [...]")

target = sys.argv[1]
cells = json.load(open(target))
index = {(c["suite"], c["key"], c["backend"], c.get("span")): i
         for i, c in enumerate(cells)}
replaced = added = 0
for patch in sys.argv[2:]:
    for c in json.load(open(patch)):
        k = (c["suite"], c["key"], c["backend"], c.get("span"))
        if k in index:
            cells[index[k]] = c
            replaced += 1
        else:
            index[k] = len(cells)
            cells.append(c)
            added += 1

if not os.path.exists(target + ".bak"):  # keep the pristine pre-merge copy
    shutil.copy(target, target + ".bak")
with open(target, "w") as f:
    json.dump(cells, f, indent=1)
print(f"{target}: {replaced} replaced, {added} added "
      f"({len(cells)} total; backup at {target}.bak)")
