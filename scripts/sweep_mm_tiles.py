"""Sweep (bm, bn, bk) for the tiled Pallas matmul on the chip.

At the default (256, 256, 512) the kernel's operand streaming traffic
(~ mp*np*K*4*(1/bm + 1/bn) bytes) is ~17 GB at n=8192 — HBM-bound where
the XLA engine balances compute and traffic; doubling the output tile
halves the traffic. VMEM at (512, 512, 1024): 2*(512*1024)*2 blocks * 4 B
double-buffered + 1 MB f32 accumulator + output copies ~= 12 MB, inside
the 16 MB budget.

Usage: python scripts/sweep_mm_tiles.py <n> "bm,bn,bk" ["bm,bn,bk" ...]
"""
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from gauss_tpu.bench.slope import matmul_chain, measure_slope_info
from gauss_tpu.kernels.matmul_pallas import matmul_pallas

n = int(sys.argv[1])
configs = [tuple(int(v) for v in s.split(",")) for s in sys.argv[2:]]
rng = np.random.default_rng(0)
a = jax.block_until_ready(
    jnp.asarray(rng.standard_normal((n, n)), jnp.float32))
b = jax.block_until_ready(
    jnp.asarray(rng.standard_normal((n, n)), jnp.float32))
truth_rows = np.asarray(a[:8], np.float64) @ np.asarray(b, np.float64)

for bm, bn, bk in configs:
    def mm(a_, b_, bm=bm, bn=bn, bk=bk):
        return matmul_pallas(a_, b_, bm=bm, bn=bn, bk=bk)

    try:
        c8 = np.asarray(mm(a, b)[:8], np.float64)
    except Exception as e:
        print(f"n={n} ({bm},{bn},{bk}): FAILED {str(e)[:120]}", flush=True)
        continue
    err = np.abs(c8 - truth_rows).max() / np.abs(truth_rows).max()
    make_chain, args = matmul_chain(a, b, mm)
    sec, k1, k2, s = measure_slope_info(make_chain, args, k_small=2,
                                        k_large=8, rounds=6)
    print(f"n={n} ({bm},{bn},{bk}): {sec*1e3:.2f} ms "
          f"(K={k1}/{k2}, slope={s}, relerr={err:.1e})", flush=True)
