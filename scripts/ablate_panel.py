"""Ablation study of the panel kernel's per-step cost on the real chip.

The two-level (deferred) kernel at h=2048/panel=256/seg=32 still runs
~170 us per call (~0.66 us per pivot step); the (seg, h) tile passes are
~35 us of that, so the floor is per-step serial bookkeeping. This strips
one per-step component at a time from a standalone copy of the kernel and
slope-times each variant, so the floor has names. The stripped variants
compute WRONG factorizations (that is the point); everything feeds the
result scalar so nothing folds away.

Usage: python scripts/ablate_panel.py [h [panel [seg]]]
"""
import sys
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")

from gauss_tpu.bench.slope import PERTURB, measure_slope_info

h = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
panel = int(sys.argv[2]) if len(sys.argv) > 2 else 256
seg = int(sys.argv[3]) if len(sys.argv) > 3 else 32


def kernel(kb_ref, t_ref, out_ref, ipiv_ref, inv_ref, minpiv_ref,
           chosen_ref, done_ref, mult_ref, pt_ref, *, ablate):
    kb = kb_ref[0]
    out_ref[:] = t_ref[:]
    lanes = lax.broadcasted_iota(jnp.int32, (1, h), 1)
    inv_ref[:] = lax.broadcasted_iota(jnp.int32, (h, 1), 0)
    chosen_ref[:] = jnp.zeros((h, 1), jnp.int32)
    done_ref[:] = (lanes < kb).astype(jnp.int32)
    minpiv_ref[0] = jnp.asarray(jnp.inf, out_ref.dtype)
    dtype = out_ref.dtype
    zero = jnp.zeros((), dtype)
    neg_inf = jnp.asarray(-jnp.inf, dtype)

    def make_step(s0, s1):
        w = s1 - s0
        subs = s0 + lax.broadcasted_iota(jnp.int32, (w, 1), 0)

        def step(j, _):
            j = j.astype(jnp.int32)
            c = kb + j
            col = out_ref[pl.ds(j, 1), :]
            if ablate == "argmax":
                p_idx = c  # no pivot search
            elif ablate == "argmax_maxmin":
                # max-reduce then first-index-of-max: two plain reductions
                # instead of one index-tracking argmax reduction.
                cand = jnp.where(done_ref[:] != 0, neg_inf, jnp.abs(col))
                mx = jnp.max(cand)
                p_idx = jnp.min(jnp.where(cand == mx, lanes,
                                          jnp.asarray(h, jnp.int32))
                                ).astype(jnp.int32)
            else:
                cand = jnp.where(done_ref[:] != 0, neg_inf, jnp.abs(col))
                p_idx = jnp.argmax(cand).astype(jnp.int32)
            ipiv_ref[j] = p_idx
            if ablate != "invstores":
                inv_ref[pl.ds(p_idx, 1), :] = jnp.full((1, 1), c, jnp.int32)
                chosen_ref[pl.ds(p_idx, 1), :] = jnp.ones((1, 1), jnp.int32)
            lane_p = lanes == p_idx
            if ablate != "pivextract":
                piv = jnp.sum(jnp.where(lane_p, col, zero))
            else:
                piv = jnp.asarray(1.0, dtype)
            if ablate != "minpiv":
                apiv = jnp.abs(piv)
                minpiv_ref[0] = jnp.minimum(
                    minpiv_ref[0], jnp.where(jnp.isnan(apiv), zero, apiv))
            if ablate != "donemask":
                done = (done_ref[:] != 0) | lane_p
                done_ref[:] = done.astype(jnp.int32)
            else:
                done = lane_p
            mult = jnp.where(done, zero, col / piv)
            mult_ref[pl.ds(j - s0, 1), :] = mult
            pt_ref[pl.ds(j - s0, 1), :] = lane_p.astype(dtype)
            if ablate != "tilepass":
                T = out_ref[pl.ds(s0, w), :]
                u = jnp.sum(jnp.where(lane_p, T, zero), axis=1, keepdims=True)
                upd = jnp.where(subs > j, u, zero)
                row_j_new = jnp.where(done, col, col / piv)
                out_ref[pl.ds(s0, w), :] = jnp.where(
                    subs == j, row_j_new, T - upd * mult)
            else:
                out_ref[pl.ds(j, 1), :] = mult
            return 0

        return step

    def deferred_update(s0, s1):
        w, wt = s1 - s0, panel - s1
        hi = lax.Precision.HIGHEST
        t0 = out_ref[pl.ds(s1, wt), :]
        m_blk = mult_ref[pl.ds(0, w), :]
        pt = pt_ref[pl.ds(0, w), :]
        dn = (((1,), (1,)), ((), ()))
        if ablate == "extract_dots":
            u = t0[:, :w] * 0.5
            lp = m_blk[:, :w] * 0.5
        else:
            u = lax.dot_general(t0, pt, dn, precision=hi,
                                preferred_element_type=dtype)
            lp = lax.dot_general(m_blk, pt, dn, precision=hi,
                                 preferred_element_type=dtype)
        if ablate != "neumann":
            p2, e = None, 1
            while e < w:
                term = lp if e == 1 else p2
                corr = jnp.dot(u, term, precision=hi,
                               preferred_element_type=dtype)
                u = u - corr if e == 1 else u + corr
                if e * 2 < w:
                    p2 = jnp.dot(term, term, precision=hi,
                                 preferred_element_type=dtype)
                e *= 2
        else:
            u = u + lp * 0.5
        out_ref[pl.ds(s1, wt), :] = t0 - jnp.dot(
            u, m_blk, precision=hi, preferred_element_type=dtype)

    for s0 in range(0, panel, seg):
        s1 = min(s0 + seg, panel)
        lax.fori_loop(s0, s1, make_step(s0, s1), 0)
        if ablate != "defupdate" and s1 < panel:
            deferred_update(s0, s1)


@partial(jax.jit, static_argnames=("ablate",))
def run_variant(p, ablate):
    kb = jnp.zeros((1,), jnp.int32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec((panel, h), lambda i, kb_ref: (0, 0))],
        out_specs=[
            pl.BlockSpec((panel, h), lambda i, kb_ref: (0, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((h, 1), lambda i, kb_ref: (0, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((h, 1), lambda i, kb_ref: (0, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((1, h), jnp.int32),
                        pltpu.VMEM((seg, h), p.dtype),
                        pltpu.VMEM((seg, h), p.dtype)],
    )
    out_t, ipiv, inv, minpiv, chosen = pl.pallas_call(
        partial(kernel, ablate=ablate),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((panel, h), p.dtype),
            jax.ShapeDtypeStruct((panel,), jnp.int32),
            jax.ShapeDtypeStruct((h, 1), jnp.int32),
            jax.ShapeDtypeStruct((1,), p.dtype),
            jax.ShapeDtypeStruct((h, 1), jnp.int32),
        ],
    )(kb, p.T)
    return (out_t[0, 0] + minpiv[0]
            + (ipiv[0] + inv[0, 0] + chosen[0, 0]).astype(p.dtype) * 1e-30)


rng = np.random.default_rng(0)
ad = jax.block_until_ready(
    jnp.asarray(rng.standard_normal((h, panel)), jnp.float32))
zero = jnp.zeros((), jnp.float32)


def make(ablate):
    def mk(k):
        @jax.jit
        def run(a_, x0):
            def body(_, x):
                return x + run_variant(a_ + x * jnp.asarray(PERTURB, a_.dtype),
                                       ablate)
            return lax.fori_loop(0, k, body, x0)
        return run
    return mk


base = None
for ablate in ("none", "argmax", "argmax_maxmin", "pivextract",
               "defupdate", "neumann", "extract_dots"):
    sec, k1, k2, s = measure_slope_info(make(ablate), (ad, zero),
                                        k_small=16, k_large=64, rounds=6)
    if ablate == "none":
        base = sec
        print(f"full kernel: {sec*1e6:.1f} us (K={k1}/{k2})", flush=True)
    else:
        print(f"without {ablate}: {sec*1e6:.1f} us "
              f"(saves {max(0.0, base - sec)*1e6:.1f})", flush=True)
