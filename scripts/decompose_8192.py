"""Decompose the n=8192 factor+solve time: panel kernel / factor / solve.

Usage: python scripts/decompose_8192.py [n [panel [chunk]]]
"""
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from gauss_tpu.bench.slope import PERTURB, measure_slope_info
from gauss_tpu.core import blocked
from gauss_tpu.kernels.panel_pallas import panel_factor_pallas

n = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
panel = int(sys.argv[2]) if len(sys.argv) > 2 else 256
chunk = int(sys.argv[3]) if len(sys.argv) > 3 else 4
rng = np.random.default_rng(0)
a = rng.standard_normal((n, n)).astype(np.float32)
a[np.arange(n), np.arange(n)] += n / 100.0
b = rng.standard_normal(n).astype(np.float32)
ad = jax.block_until_ready(jnp.asarray(a))
bd = jax.block_until_ready(jnp.asarray(b))
nb = n // panel


def report(name, make_chain, args, ks=1, kl=4):
    sec, k1, k2, s = measure_slope_info(make_chain, args, k_small=ks,
                                        k_large=kl, rounds=8)
    print(f"{name}: {sec*1e3:.2f} ms (K={k1}/{k2}, slope={s})", flush=True)
    return sec


# 1. One panel factor on an (n, panel) block, chained.
def make_panel_chain(k):
    @jax.jit
    def run(a_, x0):
        def body(_, x):
            p = lax.dynamic_slice(a_, (0, 0), (n, panel)) \
                + x * jnp.asarray(PERTURB, a_.dtype)
            out, ipiv, perm, mp = panel_factor_pallas(p, 0)
            return out[0, 0] + mp

        x = lax.fori_loop(0, k, body, x0)
        return x

    return run


t_panel = report("one panel_factor_pallas (h=n)", make_panel_chain,
                 (ad, jnp.zeros((), jnp.float32)), ks=4, kl=16)
print(f"  x nb={nb} panels (upper bound, h shrinks in groups): "
      f"{t_panel*nb*1e3:.1f} ms", flush=True)


# 2. Factor only.
def make_factor_chain(k):
    @jax.jit
    def run(a_, x0):
        def body(_, x):
            fac = blocked.lu_factor_blocked_chunked(
                a_ + x * jnp.asarray(PERTURB, a_.dtype), panel=panel,
                chunk=chunk)
            return fac.m[0, 0] + fac.min_abs_pivot

        return lax.fori_loop(0, k, body, x0)

    return run


t_factor = report(f"factor only (chunked p{panel} c{chunk})",
                  make_factor_chain, (ad, jnp.zeros((), jnp.float32)))

# 3. Solve only (factor fixed, chained solves).
fac = jax.block_until_ready(
    blocked.lu_factor_blocked_chunked(ad, panel=panel, chunk=chunk))


def make_solve_chain(k):
    @jax.jit
    def run(m, perm, mp, linv, uinv, b_, x0):
        f = blocked.BlockedLU(m, perm, mp, linv, uinv)

        def body(_, x):
            return blocked.lu_solve(f, b_ + x[0] * jnp.asarray(PERTURB,
                                                               b_.dtype))

        return jnp.sum(lax.fori_loop(0, k, body, x0))

    return run


t_solve = report("solve only", make_solve_chain,
                 (fac.m, fac.perm, fac.min_abs_pivot, fac.linv, fac.uinv,
                  bd, bd), ks=4, kl=16)
print(f"TOTAL accounted: factor {t_factor*1e3:.1f} + solve "
      f"{t_solve*1e3:.1f} ms", flush=True)
