"""Distributed-telemetry tests: multi-stream merge determinism, straggler
statistics, Chrome-trace export schema, collective-traffic accounting from
the real dist engines, and the benchmark-regression sentinel (on both
synthetic histories and the committed BENCH_r01-r05 records).

The REAL two-process path is exercised by tests/test_multihost.py (when the
jaxlib CPU backend supports cross-process collectives); these tests build
the same per-process stream shapes in one process so the merge/trace/regress
logic is covered everywhere."""

import json
import os

import numpy as np
import pytest

from gauss_tpu import obs
from gauss_tpu.obs import aggregate, regress, summarize, trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# synthetic per-process streams (the shape cli._common.metrics_run produces)

def _mh_stream(path, proc, t_unix, spans, wall):
    """One process's JSONL stream: run_start (with process fingerprint and
    wall-clock anchor), spans, run_end."""
    events = [{"type": "run_start", "run": "mhrun0001", "seq": 0, "t": 0.0,
               "time_unix": t_unix, "schema": 1, "tool": "mh",
               "process_index": proc, "process_count": 2,
               "host": f"host{proc}"}]
    seq = 1
    for name, end_t, dur, parent in spans:
        events.append({"type": "span", "run": "mhrun0001", "seq": seq,
                       "t": end_t, "name": name, "dur_s": dur,
                       "parent": parent, "depth": 1 if parent else 0})
        seq += 1
    events.append({"type": "run_end", "run": "mhrun0001", "seq": seq,
                   "t": wall, "wall_s": wall})
    with open(path, "w") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")
    return events


@pytest.fixture
def mh_streams(tmp_path):
    p0 = tmp_path / "run.p0.jsonl"
    p1 = tmp_path / "run.p1.jsonl"
    # Process 1 starts 0.25 s after process 0 (clock alignment must use
    # run_start.time_unix, not per-stream t).
    _mh_stream(p0, 0, 1000.0,
               [("solve", 0.5, 0.4, "root"), ("root", 1.0, 0.9, None)], 1.0)
    _mh_stream(p1, 1, 1000.25,
               [("solve", 0.7, 0.6, "root"), ("root", 1.1, 1.0, None)], 1.2)
    return str(p0), str(p1)


def test_merge_is_deterministic_in_file_order(mh_streams):
    p0, p1 = mh_streams
    rid_a, merged_a = aggregate.merge_streams([p0, p1])
    rid_b, merged_b = aggregate.merge_streams([p1, p0])
    assert rid_a == rid_b == "mhrun0001"
    assert merged_a == merged_b
    # Re-reading the same stream twice must not duplicate events.
    _, merged_c = aggregate.merge_streams([p0, p1, p0])
    assert merged_c == merged_a


def test_merge_aligns_clocks_and_stamps_procs(mh_streams):
    _, merged = aggregate.merge_streams(list(mh_streams))
    assert {ev["proc"] for ev in merged} == {0, 1}
    ends = {(ev["proc"], ev["type"]): ev for ev in merged}
    # Process 1's events shift by its 0.25 s later start.
    assert ends[(1, "run_start")]["t_aligned"] == pytest.approx(0.25)
    assert ends[(0, "run_start")]["t_aligned"] == pytest.approx(0.0)
    assert ends[(1, "run_end")]["t_aligned"] == pytest.approx(1.45)
    # Sorted by aligned time.
    times = [ev["t_aligned"] for ev in merged]
    assert times == sorted(times)


def test_straggler_stats(mh_streams):
    _, merged = aggregate.merge_streams(list(mh_streams))
    stats = aggregate.straggler_stats(merged)
    assert stats["processes"] == [0, 1]
    assert stats["wall_s"] == {0: 1.0, 1: 1.2}
    solve = stats["phases"]["solve"]
    assert solve["per_proc_s"] == {0: 0.4, 1: 0.6}
    assert solve["imbalance_s"] == pytest.approx(0.2)
    assert solve["skew"] == pytest.approx((0.6 - 0.4) / 0.6, abs=1e-3)
    report = aggregate.aggregate_report("mhrun0001", merged, stats)
    assert "process 0" in report and "process 1" in report
    assert "solve" in report


def test_aggregate_cli_writes_merged_stream(mh_streams, tmp_path, capsys):
    out = tmp_path / "merged.jsonl"
    rc = aggregate.main([*mh_streams, "-o", str(out), "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["run"] == "mhrun0001" and doc["processes"] == [0, 1]
    merged = obs.read_events(out)
    assert {ev["proc"] for ev in merged} == {0, 1}


def test_per_lane_coverage_on_merged_stream(mh_streams):
    """Satellite: coverage per process lane, never summed spans over one
    wall-clock (which would read >100% here: leaf totals 0.4+0.6 s against
    either single wall)."""
    _, merged = aggregate.merge_streams(list(mh_streams))
    prof = summarize.flat_profile(merged)
    lanes = prof["lanes"]
    assert lanes[0]["coverage"] == pytest.approx(0.4 / 1.0)
    assert lanes[1]["coverage"] == pytest.approx(0.6 / 1.2)
    # The run's duration is the max lane wall, not the sum.
    assert prof["wall_s"] == 1.2
    text = summarize.summarize_run(merged, "mhrun0001")
    assert "process 0: wall-clock" in text
    assert "process 1: wall-clock" in text
    assert "merged multihost stream: 2 processes" in text


# ---------------------------------------------------------------------------
# Chrome-trace export

def test_trace_export_schema_lanes_and_nesting(mh_streams, tmp_path):
    _, merged = aggregate.merge_streams(list(mh_streams))
    aggregate.write_merged(merged, tmp_path / "merged.jsonl")
    out = tmp_path / "trace.json"
    assert trace.main([str(tmp_path / "merged.jsonl"),
                       "-o", str(out)]) == 0
    doc = json.loads(out.read_text())  # loadable Chrome trace JSON
    assert isinstance(doc["traceEvents"], list)
    xs = [ev for ev in doc["traceEvents"] if ev["ph"] == "X"]
    # One lane (pid) per process.
    assert {ev["pid"] for ev in xs} == {0, 1}
    names = {ev["name"] for ev in xs}
    assert names == {"solve", "root"}
    # Nesting preserved: each lane's child interval sits inside its parent's.
    for pid in (0, 1):
        lane = {ev["name"]: ev for ev in xs if ev["pid"] == pid}
        child, parent = lane["solve"], lane["root"]
        assert parent["ts"] <= child["ts"]
        assert child["ts"] + child["dur"] <= parent["ts"] + parent["dur"] \
            + 1e-3
    # Lane metadata names the processes.
    metas = [ev for ev in doc["traceEvents"]
             if ev["ph"] == "M" and ev["name"] == "process_name"]
    assert {m["pid"] for m in metas} == {0, 1}


def test_trace_single_process_stream(tmp_path):
    out = tmp_path / "single.jsonl"
    with obs.run(metrics_out=str(out)) as rec:
        with obs.span("outer"):
            with obs.span("inner"):
                pass
    tr = trace.to_chrome_trace(obs.read_events(out), rec.run_id)
    xs = [ev for ev in tr["traceEvents"] if ev["ph"] == "X"]
    assert {ev["name"] for ev in xs} == {"outer", "inner"}
    assert all(ev["pid"] == 0 for ev in xs)


def test_trace_unknown_run_errors(tmp_path, capsys):
    f = tmp_path / "e.jsonl"
    f.write_text(json.dumps({"type": "run_start", "run": "abc", "seq": 0,
                             "t": 0.0}) + "\n")
    assert trace.main([str(f), "--run", "nope"]) == 1
    assert "not found" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# collective-traffic accounting (real engines, 8-virtual-device CPU mesh)

def test_collective_events_from_blocked_engine(tmp_path):
    from gauss_tpu.dist import gauss_dist_blocked as gdb
    from gauss_tpu.dist.mesh import make_mesh

    mesh = make_mesh(8)
    n, panel = 64, 8
    rng = np.random.default_rng(1)
    a = (rng.standard_normal((n, n)) + n * np.eye(n)).astype(np.float32)
    b = rng.standard_normal(n).astype(np.float32)
    out = tmp_path / "coll.jsonl"
    with obs.run(metrics_out=str(out)):
        np.asarray(gdb.gauss_solve_dist_blocked(a, b, mesh=mesh,
                                                panel=panel))
        # Second identical solve: the budget dedupes per (label, shapes).
        np.asarray(gdb.gauss_solve_dist_blocked(a, b, mesh=mesh,
                                                panel=panel))
    colls = [ev for ev in obs.read_events(out)
             if ev["type"] == "collective"
             and ev["label"] == "gauss_dist_blocked"]
    by_op = {ev["op"]: ev for ev in colls}
    nblocks = n // panel
    # The design claim, now telemetry: ONE all_gather per panel.
    assert by_op["all_gather"]["count"] == nblocks
    # Routing psum + back-sub psum per panel (16 for 8 panels).
    assert by_op["psum"]["count"] == 2 * nblocks
    assert all(ev["bytes"] > 0 for ev in colls)
    assert all(ev["via"] == "jaxpr" for ev in colls)
    # Dedup held: one event per op despite two identical solves.
    assert len(colls) == len(by_op)
    # And the summarizer folds them into the comms section.
    comms = summarize.comms_summary(obs.read_events(out))
    assert comms["gauss_dist_blocked"]["count"] == 3 * nblocks + \
        comms["gauss_dist_blocked"]["ops"].get("pmin", {}).get("count", 0)
    text = summarize.summarize_run(obs.read_events(out),
                                   colls[0]["run"])
    assert "collective traffic" in text and "all_gather" in text


def test_collective_budget_matches_direct_jaxpr_count(tmp_path):
    """The emitted counts must equal an independent jaxpr walk (the same
    derivation tests/test_dist_blocked.py proves the design claim from)."""
    import jax

    from gauss_tpu.dist import gauss_dist
    from gauss_tpu.dist.mesh import make_mesh
    from gauss_tpu.obs import collectives

    mesh = make_mesh(8)
    n = 32
    a = np.eye(n, dtype=np.float32)
    b = np.zeros(n, dtype=np.float32)
    staged = gauss_dist.prepare_dist(a, b, mesh)
    solver = gauss_dist._build_solver(mesh, staged[3], str(staged[0].dtype))
    budget = collectives.collective_budget(
        jax.make_jaxpr(solver)(staged[0], staged[1]))
    out = tmp_path / "b.jsonl"
    with obs.run(metrics_out=str(out)):
        np.asarray(gauss_dist.solve_dist_staged(staged, mesh))
    emitted = {ev["op"]: ev for ev in obs.read_events(out)
               if ev["type"] == "collective"}
    assert set(emitted) == set(budget)
    for op, d in budget.items():
        assert emitted[op]["count"] == d["count"]
        assert emitted[op]["bytes"] == d["bytes"]
    # Per-step protocol: >= 2 collectives per pivot step.
    total = sum(d["count"] for d in budget.values())
    assert total >= 2 * staged[3]


def test_collective_hlo_path_matmul_dist(tmp_path):
    """matmul_dist's collectives exist only after SPMD partitioning; the
    HLO path must still find the output all-gather."""
    from gauss_tpu.dist.matmul_dist import matmul_dist
    from gauss_tpu.dist.mesh import make_mesh

    mesh = make_mesh(8)
    a = np.ones((16, 16), np.float32)
    out = tmp_path / "mm.jsonl"
    with obs.run(metrics_out=str(out)):
        np.asarray(matmul_dist(a, a, mesh=mesh))
    colls = [ev for ev in obs.read_events(out)
             if ev["type"] == "collective" and ev["label"] == "matmul_dist"]
    assert colls, "HLO-derived collective budget missing"
    assert all(ev["via"] == "hlo" for ev in colls)
    assert any(ev["op"] == "all_gather" and ev["bytes"] > 0 for ev in colls)


def test_record_collective_budget_noop_inactive():
    assert obs.record_collective_budget("x", lambda: 0) is None


# ---------------------------------------------------------------------------
# environment fingerprint + multihost stream plumbing

def test_run_start_carries_environment_fingerprint(tmp_path):
    import jax

    out = tmp_path / "fp.jsonl"
    with obs.run(metrics_out=str(out), tool="fp"):
        pass
    start = [ev for ev in obs.read_events(out)
             if ev["type"] == "run_start"][0]
    assert start["tool"] == "fp"  # explicit meta untouched
    assert start["host"] and start["python"]
    assert start["jax"] == jax.__version__
    # The test session has an initialized 8-device CPU backend.
    assert start["backend"] == "cpu"
    assert start["device_count"] == 8
    assert start["process_index"] == 0


def test_resolve_metrics_stream():
    from gauss_tpu.dist import multihost

    # Single-process: passthrough.
    assert multihost.resolve_metrics_stream("m.jsonl") == ("m.jsonl", None)
    # Multihost coordinates: per-process path + shared deterministic id.
    p0, r0 = multihost.resolve_metrics_stream(
        "m.jsonl", coordinator="h:123", process_id=0)
    p1, r1 = multihost.resolve_metrics_stream(
        "m.jsonl", coordinator="h:123", process_id=1)
    assert (p0, p1) == ("m.p0.jsonl", "m.p1.jsonl")
    assert r0 == r1 and len(r0) == 12
    # A different launch (different coordinator) gets a different run id.
    _, r2 = multihost.resolve_metrics_stream("m.jsonl", coordinator="h:999",
                                             process_id=0)
    assert r2 != r0


def test_resolve_metrics_stream_env_override(monkeypatch):
    from gauss_tpu.dist import multihost

    monkeypatch.setenv("GAUSS_OBS_RUN_ID", "deadbeef0123")
    path, rid = multihost.resolve_metrics_stream(
        "m.jsonl", coordinator="h:123", process_id=1)
    assert rid == "deadbeef0123" and path == "m.p1.jsonl"


def test_obs_run_honors_env_run_id(monkeypatch, tmp_path):
    monkeypatch.setenv("GAUSS_OBS_RUN_ID", "feedface0000")
    out = tmp_path / "env.jsonl"
    with obs.run(metrics_out=str(out)) as rec:
        pass
    assert rec.run_id == "feedface0000"


# ---------------------------------------------------------------------------
# summarize --json (machine-readable summary)

def test_summarize_json_payload(tmp_path, capsys):
    out = tmp_path / "j.jsonl"
    with obs.run(metrics_out=str(out), tool="jtest") as rec:
        with obs.span("phase_a"):
            pass
        obs.emit("reported_time", name="t", seconds=1.0)
    assert summarize.main([str(out), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    summary = doc[rec.run_id]
    assert summary["meta"]["tool"] == "jtest"
    assert summary["environment"]["backend"] == "cpu"
    assert "phase_a" in summary["profile"]["phases"]
    assert summary["reported"][0]["seconds"] == 1.0
    assert summary["processes"] == [0]
    assert isinstance(summary["comms"], dict)


# ---------------------------------------------------------------------------
# the regression sentinel

def _write_history(path, values, metric="m"):
    with open(path, "w") as f:
        for i, v in enumerate(values):
            f.write(json.dumps({"metric": metric, "value": v, "unit": "s",
                                "source": f"e{i}", "kind": "bench"}) + "\n")


def test_regress_flags_30pct_slowdown_passes_epoch_noise(tmp_path):
    """The acceptance pair on a synthetic history: a 30% slowdown is out of
    band; a value inside the documented ~±10% epoch-noise spread is green."""
    hist_path = tmp_path / "h.jsonl"
    _write_history(hist_path, [0.0020, 0.0021, 0.0022, 0.0019, 0.0021])
    history = regress.load_history(hist_path)
    base = 0.0021  # the median
    bad = regress.evaluate("m", base * 1.30, history)
    assert bad["status"] == "out-of-band"
    assert "same-epoch A/B" in bad["note"]  # within the 1.5x epoch ceiling
    worse = regress.evaluate("m", base * 2.0, history)
    assert worse["status"] == "out-of-band"
    assert "code regression" in worse["note"]  # beyond the epoch ceiling
    good = regress.evaluate("m", base * 1.08, history)
    assert good["status"] == "ok"
    fast = regress.evaluate("m", base * 0.7, history)
    assert fast["status"] == "fast"  # a lucky epoch is never a regression


def test_regress_committed_history_classifies_r3_r4_swing():
    """The historical incident, replayed: r4's 2.204 ms against the r1-r3
    records (median 2.042 ms — including the lucky r3 epoch) is IN band;
    the manual bisection of docs/BENCH_STABILITY.md becomes a first-
    occurrence classification. A 30% regression against the full committed
    history is flagged."""
    hist = regress.load_history(os.path.join(REPO, "reports",
                                             "history.jsonl"))
    assert len(hist) >= 5, "committed history must be seeded from r1-r5"
    r1_r3 = [r for r in hist
             if r["source"] in ("BENCH_r01.json", "BENCH_r02.json",
                                "BENCH_r03.json")]
    v = regress.evaluate("gauss_n2048_wallclock", 0.002204, r1_r3)
    assert v["status"] == "ok", v
    # Every committed record is in band against the full history.
    for rec in hist:
        if rec["metric"] != "gauss_n2048_wallclock":
            continue
        v = regress.evaluate(rec["metric"], rec["value"], hist)
        assert v["status"] in ("ok", "fast"), (rec, v)
    # An injected 30% slowdown over the median is out of band.
    med = regress.baseline(
        [r["value"] for r in hist
         if r["metric"] == "gauss_n2048_wallclock"])["median"]
    v = regress.evaluate("gauss_n2048_wallclock", med * 1.30, hist)
    assert v["status"] == "out-of-band", v


def test_regress_ingest_bench_record(tmp_path):
    rec_path = tmp_path / "BENCH.json"
    rec_path.write_text(json.dumps({
        "parsed": {"metric": "gauss_n2048_wallclock", "value": 0.002,
                   "unit": "s", "refined_value": 0.003}}))
    records = regress.ingest_file(rec_path)
    assert {r["metric"]: r["value"] for r in records} == {
        "gauss_n2048_wallclock": 0.002,
        "gauss_n2048_wallclock:refined": 0.003}


def test_regress_ingest_cells_and_obs_stream(tmp_path):
    cells = tmp_path / "cells.json"
    cells.write_text(json.dumps([
        {"suite": "gauss-internal", "key": "64", "backend": "tpu",
         "seconds": 0.5, "verified": True, "span": "reference"},
        {"suite": "gauss-internal", "key": "64", "backend": "seq",
         "seconds": 0.0, "verified": False, "span": "reference"}]))
    records = regress.ingest_file(cells)
    # FAILED cells never become baselines.
    assert [r["metric"] for r in records] == [
        "cell:gauss-internal/64/tpu"]
    stream = tmp_path / "s.jsonl"
    stream.write_text(json.dumps(
        {"type": "cell", "run": "r", "seq": 1, "t": 0.1,
         "suite": "matmul", "key": "1024", "backend": "tpu",
         "seconds": 0.25, "verified": True, "span": "device"}) + "\n")
    records = regress.ingest_file(stream)
    assert records[0]["metric"] == "cell:matmul/1024/tpu@device"


def test_regress_history_append_is_idempotent(tmp_path):
    hist = tmp_path / "h.jsonl"
    recs = [{"metric": "m", "value": 1.0, "unit": "s", "source": "a",
             "kind": "bench"}]
    assert regress.append_history(recs, hist) == 1
    assert regress.append_history(recs, hist) == 0
    assert len(regress.load_history(hist)) == 1


def test_regress_cli_check_gate(tmp_path, capsys):
    hist = tmp_path / "h.jsonl"
    _write_history(hist, [1.0, 1.0, 1.0], metric="gauss_n2048_wallclock")
    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps({"parsed": {
        "metric": "gauss_n2048_wallclock", "value": 1.05, "unit": "s"}}))
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"parsed": {
        "metric": "gauss_n2048_wallclock", "value": 1.35, "unit": "s"}}))
    assert regress.main(["check", str(ok), "--history", str(hist)]) == 0
    assert regress.main(["check", str(bad), "--history", str(hist)]) == 1
    assert "out of band" in capsys.readouterr().out


def test_regress_min_samples_informational(tmp_path):
    hist = tmp_path / "h.jsonl"
    _write_history(hist, [1.0])
    v = regress.evaluate("m", 99.0, regress.load_history(hist))
    assert v["status"] == "no-baseline"
