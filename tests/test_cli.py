"""CLI driver tests: reference-parity surfaces and output lines.

Run in-process via each driver's main() (fast; JAX on CPU from conftest), with
one subprocess smoke test for the module entry points.
"""

import re
import subprocess
import sys

import numpy as np
import pytest

from gauss_tpu import native
from gauss_tpu.cli import gauss_external, gauss_internal, matmul, matrix_gen
from gauss_tpu.io import datfile, synthetic


def test_gauss_internal_default_backend(capsys):
    rc = gauss_internal.main(["-s", "64", "-t", "4", "--verify"])
    out = capsys.readouterr().out
    assert rc == 0
    assert re.search(r"Application time: \d+\.\d+ Secs", out)
    assert "pattern (-0.5, 0...0, 0.5) OK" in out


@pytest.mark.parametrize("backend", ["tpu-unblocked", "seq", "omp", "threads"])
def test_gauss_internal_backends(capsys, backend):
    if backend in ("seq", "omp", "threads") and not native.available():
        pytest.skip("native unavailable")
    rc = gauss_internal.main(["-s", "48", "-t", "3", "--backend", backend, "--verify"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "Application time:" in out


def test_pivoting_never_silently_ignored(capsys):
    """VERDICT r3 missing #3: an explicit first_nonzero request on a
    partial-only backend prints a notice; the default resolves per backend
    with no notice; tpu-unblocked honors the flag silently."""
    from gauss_tpu.cli import _common

    # Explicit first_nonzero on the blocked tpu backend: notice (on stderr,
    # the notice channel — stdout stays parseable) + partial.
    rc = gauss_internal.main(["-s", "32", "--backend", "tpu",
                              "--pivoting", "first_nonzero", "--verify"])
    cap = capsys.readouterr()
    assert rc == 0
    assert "always uses partial pivoting" in cap.err
    assert "partial pivoting" not in cap.out
    # Default (no flag): quiet on every backend.
    rc = gauss_internal.main(["-s", "32", "--backend", "tpu", "--verify"])
    cap = capsys.readouterr()
    assert rc == 0
    assert "partial pivoting" not in cap.out + cap.err
    # The honoring backend: no notice either way.
    rc = gauss_internal.main(["-s", "32", "--backend", "tpu-unblocked",
                              "--pivoting", "first_nonzero", "--verify"])
    cap = capsys.readouterr()
    assert rc == 0
    assert "always uses partial pivoting" not in cap.out + cap.err
    # Resolution helper semantics.
    assert _common.resolve_pivoting(None, "tpu") == "partial"
    assert _common.resolve_pivoting(None, "tpu-unblocked") == "first_nonzero"
    assert _common.resolve_pivoting("partial", "tpu-unblocked") == "partial"


def test_gauss_internal_invalid_args_fall_back(capsys):
    """Reference getopt behavior: invalid -s/-t fall back to defaults — but a
    tiny valid -s keeps the run fast, so only -t is exercised invalid here."""
    rc = gauss_internal.main(["-s", "32", "-t", "bogus", "--backend", "tpu-unblocked"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "Invalid thread count 'bogus'; using default 32." in out


def test_gauss_external(tmp_path, capsys):
    a = synthetic.internal_matrix(40)
    f = tmp_path / "m.dat"
    datfile.write_dat(f, a)
    rc = gauss_external.main([str(f), "2", "--backend", "tpu-unblocked"])
    out = capsys.readouterr().out
    assert rc == 0
    assert re.search(r"Time: \d+\.\d+ seconds", out)
    m = re.search(r"Error: (\S+)", out)
    assert m and float(m.group(1)) < 1e-3


def test_tpu_backend_ds_route_for_large_refine_budget(monkeypatch):
    """refine_iters > 2 (at or above DS_ROUTE_MIN_N) routes the tpu backend
    through the on-device double-single chain (VERDICT r3 weak #5:
    host-driven refinement paid a tunnel round trip per iteration); same
    answer, same contract. The size gate is patched down so the ds route
    actually runs at test size."""
    from gauss_tpu.cli import _common

    monkeypatch.setattr(_common, "DS_ROUTE_MIN_N", 8)
    rng = np.random.default_rng(7)
    n = 48
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    x_true = rng.standard_normal(n)
    b = a @ x_true
    x_ds, t_ds = _common.solve_with_backend(a, b, "tpu", refine_iters=4)
    x_host, t_host = _common.solve_with_backend(a, b, "tpu", refine_iters=2)
    assert t_ds > 0 and t_host > 0
    np.testing.assert_allclose(x_ds, x_true, rtol=1e-8, atol=1e-8)
    np.testing.assert_allclose(x_host, x_true, rtol=1e-8, atol=1e-8)


def test_gauss_external_missing_file(capsys):
    rc = gauss_external.main(["/nonexistent/nowhere.dat"])
    err = capsys.readouterr().err
    assert rc == 1
    assert "cannot read" in err


def test_matmul_cli(capsys):
    engines = "tpu,seq,omp" if native.available() else "tpu"
    rc = matmul.main(["96", "--engines", engines])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "TPU time:" in out
    assert "verify: OK" in out
    if native.available():
        assert "Sequential time:" in out and "OpenMP time:" in out


def test_matmul_cli_bad_engine(capsys):
    rc = matmul.main(["16", "--engines", "cuda"])
    assert rc == 1
    assert "unknown engines" in capsys.readouterr().err


def test_matrix_gen_python(capsys):
    rc = matrix_gen.main(["6", "--python"])
    out = capsys.readouterr().out
    assert rc == 0
    import io

    dense = datfile.read_dat_dense(io.StringIO(out), engine="python")
    np.testing.assert_array_equal(dense, synthetic.generator_matrix(6))


def test_module_entry_smoke():
    """The drivers are runnable as python -m modules (subprocess, CPU jax)."""
    rc = subprocess.run(
        [sys.executable, "-m", "gauss_tpu.cli.gauss_internal",
         "-s", "32", "-t", "2", "--backend", "tpu-unblocked"],
        capture_output=True, text=True, timeout=300,
        env={"PATH": "/usr/bin:/bin:/usr/local/bin", "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": "/root/repo", "HOME": "/root"})
    assert rc.returncode == 0, rc.stderr
    assert "Application time:" in rc.stdout


def test_gauss_internal_tpu_dist(capsys):
    """tpu-dist backend shards over the 8-virtual-device CPU mesh."""
    rc = gauss_internal.main(["-s", "48", "-t", "4", "--backend", "tpu-dist", "--verify"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "Application time:" in out
    assert "OK" in out


def test_gauss_internal_tpu_dist2d(capsys):
    """tpu-dist2d backend factors the device pool into a 2-D mesh."""
    rc = gauss_internal.main(
        ["-s", "48", "-t", "8", "--backend", "tpu-dist2d", "--verify"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "Application time:" in out
    assert "OK" in out


def test_gauss_external_debug_flag(tmp_path, capsys):
    """--debug: the reference's compile-time DEBUG define as a runtime flag
    (parse + pivot diagnostics around the normal output lines)."""
    import numpy as np

    from gauss_tpu.io import datfile

    f = tmp_path / "m.dat"
    rng = np.random.default_rng(3)
    datfile.write_dat(f, rng.standard_normal((24, 24)))
    rc = gauss_external.main([str(f), "--backend", "tpu", "--debug"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "DEBUG: parsed header n=24" in out
    assert "DEBUG: partial pivoting moved" in out
    assert "Time:" in out and "Error:" in out


def test_gauss_external_debug_zero_matrix(tmp_path, capsys):
    """--debug on a valid nnz=0 file must not crash or misreport a read
    failure; the solve itself then reports the singular system."""
    f = tmp_path / "z.dat"
    f.write_text("4 4 0\n0 0 0\n")
    gauss_external.main([str(f), "--backend", "tpu-unblocked", "--debug"])
    out = capsys.readouterr().out
    assert "DEBUG: parsed header n=4, nnz=0, no nonzeros" in out
    assert "cannot read" not in out


def test_gauss_external_debug_min_pivot_unclamped(tmp_path, capsys):
    """min |pivot| must come from the real U diagonal, not the identity
    padding (which clamps min_abs_pivot to <= 1 for n % panel != 0)."""
    import numpy as np

    from gauss_tpu.io import datfile

    f = tmp_path / "d.dat"
    datfile.write_dat(f, 10.0 * np.eye(8))
    rc = gauss_external.main([str(f), "--backend", "tpu", "--debug"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "min |pivot| = 1.000000e+01" in out


def test_gauss_external_singular_prints_reference_message(tmp_path, capsys):
    """Singular systems end with the reference's abort line on stderr
    (gauss_external_input.c:137) and a nonzero exit — for both native
    (LinAlgError) and device (NaN solution) engines."""
    from gauss_tpu import native

    f = tmp_path / "z.dat"
    f.write_text("4 4 0\n0 0 0\n")
    backends = ["tpu-unblocked"] + (["seq"] if native.available() else [])
    for backend in backends:
        rc = gauss_external.main([str(f), "--backend", backend])
        captured = capsys.readouterr()
        assert rc == 1, backend
        assert "The matrix is singular" in captured.err, backend


def test_matmul_cli_precision_flag(capsys):
    """--precision overrides the XLA engine's default and clamps 'high' up
    for Pallas kernels (Mosaic rejects HIGH inside kernels)."""
    rc = matmul.main(["64", "--engines", "tpu,tpu-pallas",
                      "--precision", "highest"])
    out = capsys.readouterr().out
    assert rc == 0 and out.count("verify: OK") == 2
    rc = matmul.main(["64", "--engines", "tpu-pallas", "--precision", "high"])
    assert rc == 0


def test_gauss_external_tpu_dist_backend(tmp_path, capsys):
    """External flavor through the distributed engine (8 virtual devices)."""
    import numpy as np

    from gauss_tpu.io import datfile

    f = tmp_path / "m.dat"
    rng = np.random.default_rng(5)
    datfile.write_dat(f, rng.standard_normal((48, 48)) + 8 * np.eye(48))
    rc = gauss_external.main([str(f), "8", "--backend", "tpu-dist"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "Time:" in out and "Error:" in out


def test_matmul_cli_tpu_dist_engine(capsys):
    """The pjit-sharded matmul as a CLI engine over the 8-device test mesh."""
    rc = matmul.main(["96", "--engines", "tpu-dist"])
    out = capsys.readouterr().out
    assert rc == 0 and "TPU-Dist (sharded) time:" in out and "verify: OK" in out
