"""Tests for the MXU GEMM precision sweep bench (gauss_tpu.bench.precision).

The sweep's TPU measurements live in reports/cells_precision.json; these
tests pin the machinery — cell schema, verification gating, CLI plumbing,
and the failure path — on the CPU test platform.
"""

import json

import numpy as np
import pytest

from gauss_tpu.bench import precision
from gauss_tpu.bench.grid import format_table


def test_measure_cell_schema_and_verification():
    c = precision.measure_cell(64, "highest", refine_steps=2)
    assert c.suite == "gauss-precision"
    assert c.backend == "tpu[highest]"
    assert c.span == "device"
    assert c.verified and c.error < 1e-4
    assert "gemm_precision=highest" in c.note
    assert "TF/s useful" in c.note
    # format_table must render the suite (round-3 regression: KeyError).
    assert "gauss-precision" in format_table([c])


def test_both_precisions_verify_small():
    for prec in precision.PRECISIONS:
        c = precision.measure_cell(48, prec, refine_steps=3)
        assert c.verified, (prec, c.error)


def test_main_writes_json(tmp_path):
    out = tmp_path / "cells.json"
    rc = precision.main(["--sizes", "48", "--precisions", "highest",
                         "--json", str(out)])
    assert rc == 0
    cells = json.loads(out.read_text())
    assert len(cells) == 1
    assert cells[0]["backend"] == "tpu[highest]"
    assert cells[0]["verified"] is True


def test_main_failure_path_records_cause(tmp_path, monkeypatch):
    """A crashing measurement must produce a FAILED cell with the exception
    in its note and a nonzero exit — never a lost sweep."""
    def boom(n, prec, refine_steps=3):
        raise RuntimeError("synthetic kaboom")

    monkeypatch.setattr(precision, "measure_cell", boom)
    out = tmp_path / "cells.json"
    rc = precision.main(["--sizes", "48", "--precisions", "high",
                         "--json", str(out)])
    assert rc == 1
    cells = json.loads(out.read_text())
    assert cells[0]["verified"] is False
    assert "RuntimeError: synthetic kaboom" in cells[0]["note"]
    assert cells[0]["error"] is None  # NaN serialized as null
