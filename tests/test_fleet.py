"""Supervised-fleet tests: the collective watchdog (typed deadlines around
blocking waits), sharded coordinated checkpoints (atomic shards, digest
manifests, world-size-independent assembly, last-good fallback), and the
supervisor end to end — REAL worker subprocesses killed and stalled
mid-factorization, with the acceptance invariant: the supervised job
resumes from the sharded checkpoint bit-identical to an uninterrupted
supervised run (and 1e-4-verified vs NumPy), a stalled worker is detected
within the configured deadline, and nothing ever hangs (every wait here is
deadline-bounded).

Subprocess-spawning tests keep n small — they are about the supervision
protocol, not FLOPs.
"""

import json
import os
import time

import numpy as np
import pytest

from gauss_tpu import obs
from gauss_tpu.resilience import checkpoint as ckpt
from gauss_tpu.resilience import dcheckpoint, fleet, watchdog
from gauss_tpu.verify import checks

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _system(rng, n):
    a = rng.standard_normal((n, n))
    a[np.arange(n), np.arange(n)] += float(n)
    return a, rng.standard_normal(n)


# -- watchdog --------------------------------------------------------------

def test_watchdog_off_is_inline_and_transparent():
    assert not watchdog.enabled()
    assert watchdog.guarded(lambda: 41 + 1, site="s") == 42
    with pytest.raises(KeyError):
        watchdog.guarded(lambda: {}["x"], site="s")


def test_watchdog_guarded_timeout_is_typed():
    with watchdog.deadline(0.1):
        assert watchdog.enabled()
        assert watchdog.guarded(lambda: "fast", site="s") == "fast"
        with obs.run() as rec:
            with pytest.raises(watchdog.WorkerLostError) as ei:
                watchdog.guarded(lambda: time.sleep(30), site="dist.x.solve")
    assert ei.value.site == "dist.x.solve"
    assert ei.value.deadline_s == 0.1
    evs = [e for e in rec.events if e["type"] == "watchdog"]
    assert evs and evs[0]["site"] == "dist.x.solve"
    assert not watchdog.enabled()


def test_watchdog_wait_for_ticks_and_times_out():
    ticks = []
    got = watchdog.wait_for(lambda: len(ticks) >= 2 and "ready", site="b",
                            deadline_s=10.0, poll_s=0.001,
                            on_tick=lambda: ticks.append(1))
    assert got == "ready" and len(ticks) >= 2
    with pytest.raises(watchdog.WorkerLostError):
        watchdog.wait_for(lambda: False, site="b", deadline_s=0.05,
                          poll_s=0.001)


def test_watchdog_env_activation(monkeypatch):
    monkeypatch.setenv(watchdog.ENV_VAR, "2.5")
    assert watchdog._env_deadline() == 2.5
    monkeypatch.setenv(watchdog.ENV_VAR, "junk")
    assert watchdog._env_deadline() is None


# -- lease heartbeats ------------------------------------------------------

def test_beat_noop_without_env_and_writes_lease(tmp_path, monkeypatch):
    monkeypatch.delenv(fleet.ENV_LEASE, raising=False)
    fleet.beat(phase="x")  # no env: must not write anywhere or raise
    lease = tmp_path / "leases" / "w0.json"
    monkeypatch.setenv(fleet.ENV_LEASE, str(lease))
    fleet.beat(phase="factor", group=3)
    doc = fleet.read_lease(lease)
    assert doc["phase"] == "factor" and doc["group"] == 3
    assert doc["pid"] == os.getpid() and doc["beat"] >= 1


def test_dist_engines_heartbeat_through_fleet(tmp_path, monkeypatch):
    """The four dist engines' stage hooks write the worker lease when one
    is configured — a supervised worker running a distributed solve
    heartbeats at stage boundaries without any fleet-specific plumbing."""
    from gauss_tpu.dist import (gauss_dist, gauss_dist2d, gauss_dist_blocked,
                                gauss_dist_blocked2d, make_mesh)
    from gauss_tpu.dist.mesh import make_mesh_2d

    lease = tmp_path / "w0.json"
    monkeypatch.setenv(fleet.ENV_LEASE, str(lease))
    rng = np.random.default_rng(7)
    a, b = _system(rng, 16)
    engines = [
        lambda: gauss_dist.gauss_solve_dist(a, b, mesh=make_mesh(4)),
        lambda: gauss_dist2d.gauss_solve_dist2d(a, b, mesh=make_mesh_2d(2, 2)),
        lambda: gauss_dist_blocked.gauss_solve_dist_blocked(
            a, b, mesh=make_mesh(4), panel=4),
        lambda: gauss_dist_blocked2d.gauss_solve_dist_blocked2d(
            a, b, mesh=make_mesh_2d(2, 2), panel=4),
    ]
    expect = ["gauss_dist", "gauss_dist2d", "gauss_dist_blocked",
              "gauss_dist_blocked2d"]
    for run, name in zip(engines, expect):
        if lease.exists():
            lease.unlink()
        x = np.asarray(run(), np.float64)
        assert checks.residual_norm(a, x, b, relative=True) <= 1e-3
        doc = fleet.read_lease(lease)
        assert doc and doc["engine"] == name, (name, doc)
        assert doc["phase"] == "dist_factor_solve"


# -- sharded checkpoints ---------------------------------------------------

def _factor_all(tmp_path, a32, world, **kw):
    """Run every worker's group loop to completion, in-process, round-robin
    by generation (what the subprocess lockstep does, serialized)."""
    facs = {}
    for w in range(world):
        facs[w], _ = dcheckpoint.factor_sharded(
            a32, str(tmp_path), w, world, barrier_deadline_s=30.0, **kw)
    return facs


def test_sharded_checkpoint_roundtrip_and_assembly(tmp_path, rng):
    n = 48
    a32 = _system(rng, n)[0].astype(np.float32)
    # world=1 runs lockstep-free: factor fully, leaving manifested gens.
    fac, stats = dcheckpoint.factor_sharded(a32, str(tmp_path / "w1"), 0, 1,
                                            panel=16, chunk=1,
                                            barrier_deadline_s=30.0)
    assert stats["resumed_from"] is None and stats["gens_written"] == 3
    from gauss_tpu.core import blocked
    import jax.numpy as jnp

    one_shot = blocked.lu_factor_blocked_chunked(jnp.asarray(a32), panel=16,
                                                 chunk=1)
    np.testing.assert_array_equal(np.asarray(fac.m), np.asarray(one_shot.m))
    np.testing.assert_array_equal(np.asarray(fac.linv),
                                  np.asarray(one_shot.linv))
    # The final generation is on disk and assembles to the same carry.
    meta = {"schema": ckpt.SCHEMA, "n": n, "panel": 16, "chunk": 1,
            "panel_impl": "auto", "gemm_precision": "highest",
            "dtype": "float32", "digest": ckpt._digest(a32)}
    g, manifest = dcheckpoint.last_good(str(tmp_path / "w1"), meta)
    assert g == 3 and manifest["world"] == 1
    carry = dcheckpoint.load_carry(str(tmp_path / "w1"), manifest, panel=16,
                                   npad=48)
    np.testing.assert_array_equal(carry["m"], np.asarray(fac.m))
    np.testing.assert_array_equal(carry["linvs"], np.asarray(fac.linv))


def test_sharded_checkpoint_world_change_resume(tmp_path, rng):
    """The elastic-degrade enabler: a carry checkpointed by TWO workers
    restores onto ONE (and the finished factor matches bit-identically)."""
    n = 64
    a32 = _system(rng, n)[0].astype(np.float32)
    d = str(tmp_path / "ck")
    # Simulate a 2-worker lockstep prefix: both workers step generations
    # together until the barrier would block (worker 1 must write its shard
    # before worker 0 can manifest), by interleaving single group steps.
    # Easiest faithful prefix: run worker 0 and worker 1 loops with a
    # cooperative barrier via threads.
    import threading

    facs = {}

    def run(w):
        facs[w], _ = dcheckpoint.factor_sharded(
            a32, d, w, 2, panel=16, chunk=1, barrier_deadline_s=60.0)

    ts = [threading.Thread(target=run, args=(w,)) for w in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    assert facs, "2-worker lockstep factorization did not finish"
    np.testing.assert_array_equal(np.asarray(facs[0].m),
                                  np.asarray(facs[1].m))
    # Now resume the SAME checkpoint directory with world=1 (post-shrink):
    # everything is already factored; the single worker assembles the final
    # generation written by world=2 and returns instantly.
    fac1, stats = dcheckpoint.factor_sharded(a32, d, 0, 1, panel=16,
                                             chunk=1,
                                             barrier_deadline_s=30.0)
    assert stats["resumed_from"] == 4   # nb = 4 panels, all done
    np.testing.assert_array_equal(np.asarray(fac1.m), np.asarray(facs[0].m))
    np.testing.assert_array_equal(np.asarray(fac1.linv),
                                  np.asarray(facs[0].linv))


def test_sharded_checkpoint_corrupt_shard_falls_back(tmp_path, rng):
    n = 48
    a32 = _system(rng, n)[0].astype(np.float32)
    d = str(tmp_path / "ck")
    dcheckpoint.factor_sharded(a32, d, 0, 1, panel=16, chunk=1,
                               barrier_deadline_s=30.0)
    meta = {"schema": ckpt.SCHEMA, "n": n, "panel": 16, "chunk": 1,
            "panel_impl": "auto", "gemm_precision": "highest",
            "dtype": "float32", "digest": ckpt._digest(a32)}
    gens = dcheckpoint._generations(d)
    assert len(gens) == 2   # KEEP_GENERATIONS
    top = gens[-1]
    # Truncate the newest generation's shard: its digest no longer matches
    # the manifest, so last_good falls back to the previous generation.
    shard = os.path.join(dcheckpoint.gen_dir(d, top),
                         dcheckpoint.shard_name(0, 1))
    with open(shard, "r+b") as f:
        f.truncate(64)
    with obs.run() as rec:
        g, manifest = dcheckpoint.last_good(d, meta)
    assert g == gens[-2]
    assert any(e["type"] == "checkpoint" and e.get("event") == "corrupt"
               for e in rec.events)
    # And a valid checkpoint for a DIFFERENT operand refuses, typed.
    other = dict(meta, digest="0" * 16)
    with pytest.raises(ckpt.CheckpointMismatchError):
        dcheckpoint.last_good(d, other)


def test_sharded_checkpoint_kill_between_groups_resumes(tmp_path, rng):
    """In-process kill/resume (kind=raise) for the sharded form: the carry
    survives, the resumed factor is bit-identical."""
    from gauss_tpu.resilience import inject

    n = 64
    a32 = _system(rng, n)[0].astype(np.float32)
    clean, _ = dcheckpoint.factor_sharded(a32, str(tmp_path / "clean"), 0, 1,
                                          panel=16, chunk=1,
                                          barrier_deadline_s=30.0)
    d = str(tmp_path / "killed")
    plan = inject.FaultPlan([inject.FaultSpec(
        site="fleet.worker.group", kind="raise", max_triggers=1, skip=2)])
    with inject.plan(plan):
        with pytest.raises(inject.SimulatedFaultError):
            dcheckpoint.factor_sharded(a32, d, 0, 1, panel=16, chunk=1,
                                       barrier_deadline_s=30.0)
    resumed, stats = dcheckpoint.factor_sharded(a32, d, 0, 1, panel=16,
                                                chunk=1,
                                                barrier_deadline_s=30.0)
    assert stats["resumed_from"] == 2
    for f in ("m", "perm", "min_abs_pivot", "linv", "uinv"):
        np.testing.assert_array_equal(np.asarray(getattr(clean, f)),
                                      np.asarray(getattr(resumed, f)))


# -- the supervisor, end to end (real worker subprocesses) -----------------

FLEET_KW = dict(workers=2, panel=16, chunk=1, stall_after_s=3.0,
                barrier_deadline_s=45.0, job_timeout_s=150.0)


def test_supervised_kill_resumes_bit_identical(tmp_path, rng):
    """THE acceptance path: worker 1 is os._exit-killed mid-factorization;
    the supervisor restarts it, the replacement resumes from the sharded
    checkpoint, and the job finishes bit-identical to the uninterrupted
    supervised run and 1e-4-verified vs NumPy."""
    n = 64
    a, b = _system(rng, n)
    with obs.run() as rec:
        clean = fleet.solve_supervised(a, b, **FLEET_KW)
        killed = fleet.solve_supervised(
            a, b, inject="fleet.worker.group=kill:skip=2", inject_worker=1,
            **FLEET_KW)
    assert clean.rung == "supervised" and clean.restarts == 0
    assert killed.rung == "restart" and killed.restarts == 1
    assert killed.kills == 1 and killed.recovered
    np.testing.assert_array_equal(clean.x, killed.x)   # bit-identical
    x_ref = np.linalg.solve(a, b)
    assert checks.elementwise_match(killed.x, x_ref, 1e-4)
    assert killed.rel_residual <= 1e-4
    evs = [e for e in rec.events if e["type"] == "fleet"]
    assert [e for e in evs if e.get("event") == "worker_dead"
            and e.get("cause") == "killed"]
    assert [e for e in evs if e.get("event") == "restart"]
    dones = [e for e in evs if e.get("event") == "done"]
    assert dones and dones[-1]["rung"] == "restart"
    if killed.resume_latency_s is not None:
        assert 0 < killed.resume_latency_s < 60


def test_supervised_stall_detected_within_deadline(tmp_path, rng):
    """A stalled (alive but hung) worker: the lease goes stale, the
    supervisor kills it within stall_after_s + poll jitter and the job
    still finishes verified — the watchdog/heartbeat path, distinct from
    the kill path."""
    n = 64
    a, b = _system(rng, n)
    t0 = time.monotonic()
    with obs.run() as rec:
        res = fleet.solve_supervised(
            a, b, inject="fleet.worker.group=stall:skip=2", inject_worker=1,
            **FLEET_KW)
    assert res.stalls == 1 and res.recovered
    assert res.rel_residual <= 1e-4
    assert time.monotonic() - t0 < FLEET_KW["job_timeout_s"]
    stalled = [e for e in rec.events if e["type"] == "fleet"
               and e.get("event") == "worker_stalled"]
    assert stalled and stalled[0]["worker"] == 1
    # detection bound: stale time observed by the supervisor stays within
    # the configured deadline plus scheduling slack
    assert stalled[0]["stale_s"] < FLEET_KW["stall_after_s"] + 30


@pytest.mark.slow
def test_supervised_elastic_shrink_and_local_finish(rng):
    """Elastic degrade, both rungs: with no restart budget the world
    shrinks onto the survivor; with the shrink also forbidden the
    supervisor finishes in-process. Both still bit-identical."""
    n = 64
    a, b = _system(rng, n)
    clean = fleet.solve_supervised(a, b, **FLEET_KW)
    shrunk = fleet.solve_supervised(
        a, b, inject="fleet.worker.group=kill:skip=2", inject_worker=1,
        max_restarts=0, **FLEET_KW)
    assert shrunk.rung == "shrink" and shrunk.shrinks == 1
    assert shrunk.world == 1
    np.testing.assert_array_equal(clean.x, shrunk.x)
    local = fleet.solve_supervised(
        a, b, inject="fleet.worker.group=kill:skip=2", inject_worker=1,
        max_restarts=0, min_workers=2, **FLEET_KW)
    assert local.rung == "local_finish" and local.world == 0
    np.testing.assert_array_equal(clean.x, local.x)


def test_exit_cause_vocabulary_and_restart_budget():
    """Regression (ISSUE 19 satellite): a graceful SIGTERM drain must be
    DISTINGUISHED from a crash and must not spend max_restarts — before
    the fix every nonzero rc was 'killed' and a rolling drain could
    exhaust the budget."""
    from gauss_tpu.resilience import inject as _inject

    assert fleet.exit_cause(0) == "clean"
    assert fleet.exit_cause(fleet.DRAIN_EXIT) == "drained"
    assert fleet.exit_cause(fleet.PEER_LOST_EXIT) == "peer_lost"
    assert fleet.exit_cause(fleet.CONFIG_EXIT) == "config"
    assert fleet.exit_cause(_inject.KILL_EXIT_CODE) == "killed"
    assert fleet.exit_cause(1) == "crashed"
    assert fleet.exit_cause(-9) == "crashed"  # signal death

    # budget accounting: real failures spend it, drains/peer-lost don't
    assert fleet.counts_against_restart_budget("killed")
    assert fleet.counts_against_restart_budget("crashed")
    assert fleet.counts_against_restart_budget("stalled")
    assert not fleet.counts_against_restart_budget("drained")
    assert not fleet.counts_against_restart_budget("peer_lost")
    assert not fleet.counts_against_restart_budget("clean")
    # the three sentinel codes never collide
    assert len({fleet.DRAIN_EXIT, fleet.PEER_LOST_EXIT, fleet.CONFIG_EXIT,
                _inject.KILL_EXIT_CODE, 0}) == 5


def test_fleet_bad_request_and_config():
    with pytest.raises(ValueError):
        fleet.solve_supervised(np.ones((4, 3)), np.ones(4))
    with pytest.raises(ValueError):
        fleet.solve_supervised(np.ones((4, 4)), np.ones(4), workers=0)


# -- CLI / summary / regress wiring ----------------------------------------

def test_fleet_history_records_shape():
    recs = fleet.history_records(
        {"rung_index": 1, "resume_latency_s": 0.8, "restarts": 1,
         "stalls": 1, "wall_s": 12.5})
    assert ("fleet:rung_depth", 2, "rung") in recs
    assert ("fleet:resume_latency_s", 0.8, "s") in recs
    assert ("fleet:restarts", 2, "count") in recs
    assert ("fleet:s_per_solve", 12.5, "s") in recs
    assert fleet.history_records({}) == []


def test_fleet_cli_end_to_end(tmp_path):
    """gauss-fleet with an injected kill: summary is regress-ingestable,
    the metrics stream renders a fleet section, history appends."""
    from gauss_tpu.obs import regress, summarize

    summary_path = tmp_path / "fleet.json"
    metrics_path = tmp_path / "fleet.jsonl"
    history_path = tmp_path / "history.jsonl"
    rc = fleet.main([
        "-s", "48", "--workers", "2", "--panel", "16", "--chunk", "1",
        "--seed", "7", "--inject", "fleet.worker.group=kill:skip=1",
        "--inject-worker", "1", "--job-timeout", "150",
        "--summary-json", str(summary_path),
        "--metrics-out", str(metrics_path),
        "--history", str(history_path)])
    assert rc == 0
    summary = json.loads(summary_path.read_text())
    assert summary["kind"] == "fleet_solve"
    assert summary["verified"] and summary["restarts"] == 1
    assert summary["rung"] == "restart"
    recs = regress.ingest_file(summary_path)
    assert recs and all(r["kind"] == "fleet" for r in recs)
    assert any(r["metric"] == "fleet:rung_depth" and r["value"] == 2
               for r in recs)
    history = regress.load_history(history_path)
    assert any(r["metric"].startswith("fleet:") for r in history)
    events = obs.read_events(metrics_path)
    fs = summarize.fleet_summary(events)
    assert fs["restarts"] == 1 and fs["solves"] == 1
    assert fs["rung"] == "restart"
    assert fs["deaths"]["by_cause"].get("killed") == 1
    run_id = events[0]["run"]
    text = summarize.summarize_events(events, run_id)
    assert "fleet:" in text and "restart" in text
    payload = summarize.run_summary(events, run_id)
    json.dumps(payload)
    assert payload["fleet"]["restarts"] == 1


def test_fleet_summary_empty_without_events(tmp_path):
    from gauss_tpu.obs import summarize

    with obs.run(metrics_out=str(tmp_path / "plain.jsonl")) as rec:
        obs.emit("custom")
    events = obs.read_events(tmp_path / "plain.jsonl")
    assert summarize.fleet_summary(events) == {}
    assert "fleet:" not in summarize.summarize_events(events, rec.run_id)
