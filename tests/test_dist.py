"""Distributed engine tests on the 8-virtual-device CPU mesh (conftest)."""

import jax
import numpy as np
import pytest

from gauss_tpu.core.gauss import gauss_solve
from gauss_tpu.dist import gauss_dist, matmul_dist, make_mesh
from gauss_tpu.dist.mesh import make_mesh_2d
from gauss_tpu.io import synthetic
from gauss_tpu.verify import checks


def test_eight_devices_available():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"


@pytest.mark.parametrize("nshards", [2, 4, 8])
def test_dist_matches_oracle(rng, nshards):
    n = 64
    a = rng.standard_normal((n, n))
    b = rng.standard_normal(n)
    mesh = make_mesh(nshards)
    x = np.asarray(gauss_dist.gauss_solve_dist(a, b, mesh=mesh))
    x_ref = np.asarray(gauss_solve(a, b, pivoting="partial"))
    np.testing.assert_allclose(x, x_ref, rtol=1e-9, atol=1e-9)


def test_dist_non_multiple_padding(rng):
    """n not divisible by the shard count exercises the identity padding."""
    n = 50
    a = rng.standard_normal((n, n))
    b = rng.standard_normal(n)
    x = np.asarray(gauss_dist.gauss_solve_dist(a, b, mesh=make_mesh(8)))
    np.testing.assert_allclose(x, np.linalg.solve(a, b), rtol=1e-8, atol=1e-8)


def test_dist_internal_pattern():
    n = 128
    a = synthetic.internal_matrix(n)
    b = synthetic.internal_rhs(n)
    x = np.asarray(gauss_dist.gauss_solve_dist(a, b, mesh=make_mesh(8)))
    assert checks.internal_pattern_ok(x, atol=1e-8)


def test_dist_needs_cross_shard_swaps():
    """A matrix whose partial pivots always live on a different shard than
    the pivot position — the cross-shard row-swap path must fire."""
    rng = np.random.default_rng(0)
    n = 32
    # Reverse-dominant: row n-1-i has the largest entry in column i.
    a = rng.standard_normal((n, n)) * 0.1
    for i in range(n):
        a[n - 1 - i, i] = 10.0 + i
    b = rng.standard_normal(n)
    x = np.asarray(gauss_dist.gauss_solve_dist(a, b, mesh=make_mesh(4)))
    np.testing.assert_allclose(x, np.linalg.solve(a, b), rtol=1e-9, atol=1e-9)


def test_dist_f32(rng):
    n = 64
    a = (rng.standard_normal((n, n)) + n * np.eye(n)).astype(np.float32)
    b = rng.standard_normal(n).astype(np.float32)
    x = gauss_dist.gauss_solve_dist(a, b, mesh=make_mesh(8))
    assert x.dtype == np.float32
    np.testing.assert_allclose(
        np.asarray(x, np.float64),
        np.linalg.solve(a.astype(np.float64), b.astype(np.float64)),
        rtol=1e-3, atol=1e-3)


def test_mesh_too_many_shards():
    with pytest.raises(ValueError, match="devices"):
        make_mesh(64)


def test_matmul_dist_1d(rng):
    a = rng.standard_normal((96, 96))
    b = rng.standard_normal((96, 96))
    c = np.asarray(matmul_dist(a, b, mesh=make_mesh(8)))
    np.testing.assert_allclose(c, a @ b, rtol=1e-10)


def test_matmul_dist_2d(rng):
    a = rng.standard_normal((64, 64))
    b = rng.standard_normal((64, 64))
    c = np.asarray(matmul_dist(a, b, mesh=make_mesh_2d(4, 2)))
    np.testing.assert_allclose(c, a @ b, rtol=1e-10)


def test_matmul_dist_staged_chains_under_jit(rng):
    """The staged form must be traceable inside one jitted fori_loop — the
    device-span K-chain the bench grid times (the one-shot engine's per-call
    device_put is what broke the first dist-matmul device cells)."""
    import jax

    from gauss_tpu.bench.slope import matmul_chain
    from gauss_tpu.dist.matmul_dist import matmul_dist_staged

    a = rng.standard_normal((96, 64)).astype(np.float32)
    b = rng.standard_normal((64, 32)).astype(np.float32)
    a_dev, b_dev, c0, mm = matmul_dist_staged(a, b, mesh=make_mesh(8))
    # Pure traced product matches the host truth (pad rows beyond 96 are 0).
    c = np.asarray(jax.jit(mm)(a_dev, b_dev))[:96]
    np.testing.assert_allclose(c, a @ b, rtol=1e-4, atol=1e-4)
    # And the chain form compiles + runs: K=3 perturbed products.
    make_chain, args = matmul_chain(a_dev, b_dev, mm, c0=c0)
    out = jax.block_until_ready(make_chain(3)(*args))
    assert np.isfinite(float(out))


def test_matmul_dist_staged_rejects_vector_rhs(rng):
    from gauss_tpu.dist.matmul_dist import matmul_dist_staged

    with pytest.raises(ValueError, match="matrix operands"):
        matmul_dist_staged(rng.standard_normal((8, 8)),
                           rng.standard_normal(8), mesh=make_mesh(8))


def test_cyclic_perm_roundtrip():
    perm = gauss_dist._cyclic_perm(16, 4)
    # shard d's block holds global rows l*4 + d
    assert list(perm[:4]) == [0, 4, 8, 12]
    assert sorted(perm.tolist()) == list(range(16))
