"""2-D cyclic-sharded distributed gauss vs the single-device oracle.

Runs on the 8 virtual CPU devices from conftest (SURVEY.md §4 implication:
sharding must be unit-testable without a pod)."""

import numpy as np
import pytest

from gauss_tpu.core.gauss import gauss_solve
from gauss_tpu.dist.gauss_dist2d import gauss_solve_dist2d
from gauss_tpu.dist.mesh import make_mesh_2d
from gauss_tpu.io import synthetic
from gauss_tpu.verify import checks


@pytest.mark.parametrize("shape", [(2, 2), (4, 2), (2, 4), (8, 1), (1, 8)])
def test_dist2d_matches_oracle(rng, shape):
    n = 24  # multiple of lcm for every mesh shape above
    a = rng.standard_normal((n, n))
    b = rng.standard_normal(n)
    mesh = make_mesh_2d(*shape)
    x = np.asarray(gauss_solve_dist2d(a, b, mesh=mesh))
    np.testing.assert_allclose(x, np.asarray(gauss_solve(a, b)), rtol=1e-9)


def test_dist2d_non_multiple_padding(rng):
    # n = 23 is a multiple of neither mesh dimension -> identity padding path.
    n = 23
    a = rng.standard_normal((n, n))
    b = rng.standard_normal(n)
    mesh = make_mesh_2d(4, 2)
    x = np.asarray(gauss_solve_dist2d(a, b, mesh=mesh))
    np.testing.assert_allclose(x, np.linalg.solve(a, b), rtol=1e-8)


def test_dist2d_internal_pattern():
    n = 32
    a = synthetic.internal_matrix(n)
    b = synthetic.internal_rhs(n)
    x = np.asarray(gauss_solve_dist2d(a, b, mesh=make_mesh_2d(2, 4)))
    assert checks.internal_pattern_ok(x, atol=1e-8)


def test_dist2d_needs_cross_shard_swaps():
    # Zero diagonal everywhere: every step must pivot to a row owned by a
    # different mesh row than the diagonal's owner.
    n = 16
    a = np.fliplr(np.diag(np.arange(1.0, n + 1)))
    x_true = np.arange(1.0, n + 1)
    b = a @ x_true
    x = np.asarray(gauss_solve_dist2d(a, b, mesh=make_mesh_2d(2, 2)))
    np.testing.assert_allclose(x, x_true, rtol=1e-10)


def test_dist2d_f32(rng):
    n = 32
    a = rng.standard_normal((n, n)).astype(np.float32)
    a += n * np.eye(n, dtype=np.float32)  # well-conditioned for f32
    b = rng.standard_normal(n).astype(np.float32)
    x = np.asarray(gauss_solve_dist2d(a, b, mesh=make_mesh_2d(2, 2)))
    assert x.dtype == np.float32
    np.testing.assert_allclose(
        a.astype(np.float64) @ x, b, rtol=0, atol=1e-4)


def test_dist2d_rejects_1d_mesh():
    from gauss_tpu.dist.mesh import make_mesh

    with pytest.raises(ValueError, match="2-D mesh"):
        gauss_solve_dist2d(np.eye(4), np.ones(4), mesh=make_mesh(4))


def test_dist2d_default_mesh(rng):
    # Default mesh factors the 8 visible devices into 4x2.
    n = 16
    a = rng.standard_normal((n, n))
    b = rng.standard_normal(n)
    x = np.asarray(gauss_solve_dist2d(a, b))
    np.testing.assert_allclose(x, np.linalg.solve(a, b), rtol=1e-8)
