"""Telemetry subsystem tests: registry/JSONL round trip, span nesting,
numerical-health monitors, the summarizer, and the CLI --metrics-out path.
All pure-CPU (conftest pins JAX_PLATFORMS=cpu); no device required."""

import json

import numpy as np
import pytest

from gauss_tpu import obs
from gauss_tpu.obs import summarize


def _events(path):
    return obs.read_events(path)


def test_registry_roundtrip_through_jsonl(tmp_path):
    out = tmp_path / "run.jsonl"
    with obs.run(metrics_out=str(out), tool="test") as rec:
        obs.counter("solves", 2)
        obs.counter("solves")
        obs.gauge("panel", 128)
        obs.histogram("lat", 0.25)
        obs.histogram("lat", 0.75)
        obs.emit("custom", payload="x")
    events = _events(out)
    assert all(ev["run"] == rec.run_id for ev in events)
    by_type = {}
    for ev in events:
        by_type.setdefault(ev["type"], []).append(ev)
    assert by_type["run_start"][0]["tool"] == "test"
    assert by_type["run_end"][0]["wall_s"] > 0
    assert by_type["custom"][0]["payload"] == "x"
    metrics = {(m["kind"], m["name"]): m for m in by_type["metric"]}
    assert metrics[("counter", "solves")]["value"] == 3
    assert metrics[("gauge", "panel")]["value"] == 128
    hist = metrics[("histogram", "lat")]
    assert hist["count"] == 2 and hist["min"] == 0.25 and hist["max"] == 0.75
    # Valid JSON on every line (the file IS the interface).
    for line in out.read_text().strip().split("\n"):
        json.loads(line)


def test_jsonl_append_multiple_runs(tmp_path):
    out = tmp_path / "multi.jsonl"
    with obs.run(metrics_out=str(out)) as r1:
        obs.emit("e")
    with obs.run(metrics_out=str(out)) as r2:
        obs.emit("e")
    runs = {ev["run"] for ev in _events(out)}
    assert runs == {r1.run_id, r2.run_id}


def test_span_nesting_and_parents(tmp_path):
    out = tmp_path / "spans.jsonl"
    with obs.run(metrics_out=str(out)):
        with obs.span("outer"):
            with obs.span("inner"):
                pass
            obs.record_span("measured", 0.5)
        obs.record_span("top", 1.0)
    spans = {ev["name"]: ev for ev in _events(out) if ev["type"] == "span"}
    assert spans["inner"]["parent"] == "outer" and spans["inner"]["depth"] == 1
    assert spans["measured"]["parent"] == "outer"
    assert spans["outer"]["parent"] is None and spans["outer"]["depth"] == 0
    assert spans["top"]["parent"] is None
    assert spans["measured"]["dur_s"] == 0.5
    # outer covers inner+measured and must be excluded from the leaf profile.
    prof = summarize.flat_profile(list(spans.values()))
    assert "outer" not in prof["phases"]
    assert set(prof["phases"]) == {"inner", "measured", "top"}
    assert prof["span_total_s"] == pytest.approx(
        1.5 + spans["inner"]["dur_s"])


def test_hooks_are_noops_without_recorder():
    assert obs.active() is None
    obs.counter("x")
    obs.gauge("x", 1)
    obs.record_span("x", 1.0)
    obs.emit("x")
    with obs.span("x"):
        pass
    assert obs.record_solve_health(x=np.ones(3)) is None
    assert obs.active() is None


def test_nested_run_reuses_outer_recorder(tmp_path):
    out = tmp_path / "nested.jsonl"
    with obs.run(metrics_out=str(out)) as outer:
        with obs.run() as inner:  # no metrics_out -> same recorder
            assert inner is outer
            obs.emit("from_inner")
        # Outer run still active after the nested exit.
        assert obs.active() is outer
    types = [ev["type"] for ev in _events(out)]
    assert "from_inner" in types and types.count("run_end") == 1


def test_health_monitors_flag_singular_and_nan_system(tmp_path):
    from gauss_tpu.core import blocked

    out = tmp_path / "health.jsonl"
    n = 12
    rng = np.random.default_rng(0)
    good = (rng.standard_normal((n, n)) + n * np.eye(n)).astype(np.float32)
    singular = np.ones((n, n), np.float32)  # rank 1
    b = np.ones(n, np.float32)
    with obs.run(metrics_out=str(out)):
        fac = blocked.lu_factor_blocked(good, panel=4)
        x = blocked.lu_solve(fac, b)
        h_good = obs.record_solve_health(a=good, x=x, b=b, factors=fac, n=n,
                                         backend="tpu")
        fac_s = blocked.lu_factor_blocked(singular, panel=4)
        x_s = blocked.lu_solve(fac_s, b)
        h_bad = obs.record_solve_health(a=singular, x=x_s, b=b,
                                        factors=fac_s, n=n, backend="tpu")
    assert not h_good["nan"] and h_good["min_abs_pivot"] > 0
    assert h_good["residual"] < 1e-3 and h_good["growth_factor"] > 0
    # The singular system: zero pivot recorded, NaN solution flagged.
    assert h_bad["loop_min_abs_pivot"] == 0.0
    assert h_bad["nan"]
    health = [ev for ev in _events(out) if ev["type"] == "health"]
    assert len(health) == 2
    # NaN residual survives the JSON round trip as the string "nan".
    assert health[1]["residual"] == "nan"


def test_min_pivot_reads_real_diagonal_not_padding(tmp_path):
    """Identity padding clamps the loop-recorded min at <= 1; the health
    monitor must report the true U diagonal (same trap as the
    gauss_external --debug path)."""
    from gauss_tpu.core import blocked

    n = 6  # pads to 8 with panel=8 below
    a = (10.0 * np.eye(n)).astype(np.float32)
    with obs.run():
        fac = blocked.lu_factor_blocked(a, panel=8)
        h = obs.record_solve_health(a=a, factors=fac, n=n, backend="tpu")
    assert h["min_abs_pivot"] == pytest.approx(10.0)
    assert h["loop_min_abs_pivot"] == pytest.approx(1.0)  # the padded steps


def test_summarizer_on_golden_events_file(tmp_path):
    golden = tmp_path / "golden.jsonl"
    events = [
        {"type": "run_start", "run": "r1", "seq": 0, "t": 0.0,
         "tool": "golden"},
        {"type": "config", "run": "r1", "seq": 1, "t": 0.0, "n": 64},
        {"type": "span", "run": "r1", "seq": 2, "t": 0.1,
         "name": "initMatrix", "dur_s": 0.1, "parent": None, "depth": 0},
        {"type": "span", "run": "r1", "seq": 3, "t": 0.9,
         "name": "computeGauss", "dur_s": 0.8, "parent": None, "depth": 0},
        {"type": "reported_time", "run": "r1", "seq": 4, "t": 0.9,
         "name": "Application time", "seconds": 0.9},
        {"type": "health", "run": "r1", "seq": 5, "t": 0.95,
         "min_abs_pivot": 0.5, "growth_factor": 2.0, "residual": 1e-6,
         "nan": False, "backend": "tpu"},
        {"type": "vmem_estimate", "run": "r1", "seq": 6, "t": 0.95,
         "label": "panel_kernel", "bytes": 100, "budget": 200, "fits": True},
        {"type": "run_end", "run": "r1", "seq": 7, "t": 1.0, "wall_s": 1.0},
    ]
    golden.write_text("\n".join(json.dumps(e) for e in events) + "\n")
    rc = summarize.main([str(golden)])
    assert rc == 0
    text = summarize.summarize_events(obs.read_events(golden))
    assert "run r1" in text and "flat profile" in text
    assert "computeGauss" in text and "initMatrix" in text
    assert "Application time" in text
    assert "min_abs_pivot=0.5" in text and "growth_factor=2" in text
    assert "panel_kernel" in text
    # The leaf total (0.9) sits within 10% of the run wall-clock (1.0).
    prof = summarize.flat_profile(events)
    assert prof["span_total_s"] == pytest.approx(0.9)
    assert abs(prof["span_total_s"] - prof["wall_s"]) / prof["wall_s"] <= 0.1


def test_summarizer_cli_errors(tmp_path, capsys):
    assert summarize.main([str(tmp_path / "missing.jsonl")]) == 1
    f = tmp_path / "e.jsonl"
    f.write_text(json.dumps({"type": "run_start", "run": "abc", "seq": 0,
                             "t": 0.0}) + "\n")
    assert summarize.main([str(f), "--run", "nope"]) == 1
    assert "not found" in capsys.readouterr().err


def test_phase_timer_bridges_into_obs(tmp_path):
    from gauss_tpu.utils.profiling import PhaseTimer

    out = tmp_path / "pt.jsonl"
    with obs.run(metrics_out=str(out)):
        pt = PhaseTimer()
        with pt.phase("phaseA"):
            pass
        silent = PhaseTimer(emit=False)
        with silent.phase("phaseB"):
            pass
    names = [ev["name"] for ev in _events(out) if ev["type"] == "span"]
    assert "phaseA" in names and "phaseB" not in names


def test_vmem_estimates_recorded_from_blocked(tmp_path):
    from gauss_tpu.core import blocked

    out = tmp_path / "vmem.jsonl"
    with obs.run(metrics_out=str(out)):
        blocked.panel_fits_vmem(4096, 256)
        blocked.panel_fits_vmem(65536, 32)  # narrow-width fallback rung
        blocked.fits_single_chip(2048)
    evs = [ev for ev in _events(out) if ev["type"] == "vmem_estimate"]
    labels = [ev["label"] for ev in evs]
    assert labels.count("panel_kernel") == 2
    assert "single_chip_hbm" in labels
    narrow = [ev for ev in evs if ev.get("panel") == 32][0]
    # The conservative narrow-panel fallback (ADVICE r5): overhead
    # max(220, 55000//32) = 1718 B/row, not the flat 220.
    assert narrow["bytes"] == 65536 * (32 * 4 + max(220, 55_000 // 32))
    assert narrow["fits"] is False


def test_phased_factorization_matches_and_records_spans(tmp_path):
    from gauss_tpu.core import blocked
    from gauss_tpu.utils.profiling import PhaseTimer

    rng = np.random.default_rng(3)
    n = 40
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal(n).astype(np.float32)
    out = tmp_path / "phased.jsonl"
    with obs.run(metrics_out=str(out)):
        pt = PhaseTimer()
        fac = blocked.lu_factor_blocked_phased(a, panel=16, timer=pt)
    ref = blocked.lu_factor_blocked(a, panel=16)
    np.testing.assert_allclose(np.asarray(fac.m), np.asarray(ref.m),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(fac.perm), np.asarray(ref.perm))
    x = blocked.lu_solve(fac, b)
    resid = np.linalg.norm(np.asarray(a, np.float64) @ np.asarray(x, np.float64)
                           - np.asarray(b, np.float64))
    assert resid < 1e-3
    assert {"panel_factor", "pivot_apply", "trailing_update"} <= set(pt.seconds)
    names = {ev["name"] for ev in _events(out) if ev["type"] == "span"}
    assert {"panel_factor", "pivot_apply", "trailing_update"} <= names


def test_record_cost_on_jitted_fn(tmp_path):
    import jax

    out = tmp_path / "cost.jsonl"
    f = jax.jit(lambda x: x @ x)
    arg = np.ones((16, 16), np.float32)
    with obs.run(metrics_out=str(out)):
        summary = obs.record_cost("square", f, arg)
    assert summary is not None and summary.get("flops", 0) > 0
    cost = [ev for ev in _events(out) if ev["type"] == "cost"]
    assert cost and cost[0]["label"] == "square"


def test_cli_metrics_out_smoke(tmp_path, capsys):
    """The acceptance path: one gauss_internal run with --metrics-out yields
    a summarizable JSONL whose leaf-span total covers the run wall-clock
    within 10% and whose health event carries min-pivot/growth/residual.

    WARM-UP-AWARE (ISSUE 13 satellite): an unrecorded identical run first,
    so cold-jax initialization and first compiles happen OUTSIDE the
    measured run's wall clock. Without it this test was order-dependent —
    green inside the ordered suite (earlier tests warm the caches), ~40%
    leaf-span coverage when run standalone."""
    from gauss_tpu.cli import gauss_internal

    gauss_internal.main(["-s", "64", "-t", "2", "--verify"])  # warm-up
    capsys.readouterr()
    out = tmp_path / "cli.jsonl"
    rc = gauss_internal.main(["-s", "64", "-t", "2", "--verify",
                              "--metrics-out", str(out)])
    stdout = capsys.readouterr().out
    assert rc == 0
    assert "Metrics: run" in stdout
    events = obs.read_events(out)
    prof = summarize.flat_profile(events)
    assert "computeGauss" in prof["phases"]
    assert prof["wall_s"] and prof["span_total_s"] > 0
    coverage = prof["span_total_s"] / prof["wall_s"]
    # 0.85, not 0.9: the warmed run's wall is ~35 ms, of which ~3 ms is
    # fixed between-span host glue (argument staging, event flushing) that
    # no leaf span covers — measured 0.90-0.92 across standalone runs,
    # occasionally grazing 0.90. The failure mode this line exists to
    # catch (cold-compile wall inflation, a span going missing) reads
    # ~0.40.
    assert 0.85 <= coverage <= 1.01, f"leaf spans cover {coverage:.1%} of run"
    health = [ev for ev in events if ev["type"] == "health"]
    assert health, "no health event recorded"
    h = health[0]
    assert h["min_abs_pivot"] > 0 and "growth_factor" in h
    assert h["residual"] == 0 or h["residual"] < 1e-4
    reported = [ev for ev in events if ev["type"] == "reported_time"]
    assert reported and reported[0]["name"] == "Application time"
    text = summarize.summarize_events(events)
    assert "flat profile" in text and "numerical health" in text


def test_bench_grid_metrics_out(tmp_path):
    """bench.grid --metrics-out: per-cell events recorded, JSON rows carry
    the telemetry run_id."""
    from gauss_tpu.bench import grid

    jsonp = tmp_path / "cells.json"
    metrics = tmp_path / "grid.jsonl"
    rc = grid.main(["--suite", "gauss-internal", "--keys", "32",
                    "--backends", "tpu-unblocked",
                    "--json", str(jsonp), "--metrics-out", str(metrics)])
    assert rc == 0
    cells = json.loads(jsonp.read_text())
    events = obs.read_events(metrics)
    run_ids = {ev["run"] for ev in events}
    assert cells[0]["run_id"] in run_ids
    cell_events = [ev for ev in events if ev["type"] == "cell"]
    assert cell_events and cell_events[0]["backend"] == "tpu-unblocked"
    assert cell_events[0]["verified"] is True
