"""gauss-lint tests: the jaxpr auditor (callback-free plain path, bf16
accumulation, f64 confinement, donation survival, registry completeness),
the lockset checker's edge cases (nested withs, lock released
mid-function, thread confinement, annotated-but-never-locked, waivers,
the CAS-terminal rule), the drift lint rules against tampered tmp roots,
the baseline grandfather-ratchet semantics, the ``kind: lint_report``
regress ingest, and the CLI both ways: the default run must be CLEAN on
this repo with the committed empty baseline, and the seeded-violation
fixture module (``analysis/selftest.py``) must fail every rule with the
exact ``file:line`` it records.
"""

import json
import os
import textwrap

import pytest

from gauss_tpu.analysis import (
    Finding,
    check_against_baseline,
    cli,
    driftlint,
    history_records,
    jaxpr_audit,
    load_baseline,
    lockset,
    save_baseline,
    selftest,
)
from gauss_tpu.core import entrypoints as ep

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SELFTEST = "gauss_tpu/analysis/selftest.py"
SELFTEST_SPEC = "gauss_tpu.analysis.selftest:SELFTEST_ENTRIES"


def _rules(findings):
    return {f.rule for f in findings}


# -- jaxpr auditor -----------------------------------------------------------

def test_registered_entries_all_pass():
    """The acceptance criterion: every registered fast-path entry traces
    clean — callback-free, bf16-accumulate-f32, f64-confined, donation
    alive — across ALL entries, not sampled sizes."""
    findings, stats = jaxpr_audit.run()
    assert findings == [], [f.format() for f in findings]
    assert stats["traced"] >= 20
    assert stats["eqns_checked"] > 1000


def test_callback_entry_flags():
    entries = selftest.selftest_entries()
    cb = next(e for e in entries if e.name == "selftest/callback")
    findings, checked = jaxpr_audit.audit_entry(cb)
    assert checked > 0
    hits = [f for f in findings if f.rule == "jaxpr.callback"]
    assert len(hits) == 1
    exp_path, exp_line = selftest.expected_findings()["jaxpr.callback"]
    assert (hits[0].path, hits[0].line) == (exp_path, exp_line)


def test_host_stepped_entry_allows_callback():
    """The same callback-carrying program is FINE when the entry is
    registered host-stepped — the exemption is declared, not heuristic."""
    import dataclasses

    cb = next(e for e in selftest.selftest_entries()
              if e.name == "selftest/callback")
    blessed = dataclasses.replace(cb, host_stepped=True)
    findings, _ = jaxpr_audit.audit_entry(blessed)
    assert not [f for f in findings if f.rule == "jaxpr.callback"]


def test_bf16_dot_entry_flags():
    entries = selftest.selftest_entries()
    dot = next(e for e in entries if e.name == "selftest/bf16_dot")
    findings, _ = jaxpr_audit.audit_entry(dot)
    hits = [f for f in findings if f.rule == "jaxpr.bf16_accum"]
    assert len(hits) == 1
    exp = selftest.expected_findings()["jaxpr.bf16_accum"]
    assert (hits[0].path, hits[0].line) == exp
    assert "preferred_element_type" in hits[0].message


def test_f64_entry_flags_and_refinement_exempts():
    import dataclasses

    f64e = next(e for e in selftest.selftest_entries()
                if e.name == "selftest/f64")
    findings, _ = jaxpr_audit.audit_entry(f64e)
    hits = [f for f in findings if f.rule == "jaxpr.f64"]
    assert hits
    exp = selftest.expected_findings()["jaxpr.f64"]
    assert (hits[0].path, hits[0].line) == exp
    refined = dataclasses.replace(f64e, refinement=True)
    findings, _ = jaxpr_audit.audit_entry(refined)
    assert not [f for f in findings if f.rule == "jaxpr.f64"]


def test_dropped_donation_flags():
    """An entry that DECLARES donation but lowers without the alias must
    flag jaxpr.donation — the silently-dropped-donation case CPU
    semantics would otherwise hide."""
    def lower_without_alias():
        import jax
        import jax.numpy as jnp

        return jax.jit(lambda m: m * 2.0).lower(
            jnp.zeros((4, 4), jnp.float32))

    entry = ep.EntryPoint("selftest/dropped_donation",
                          lower_donating=lower_without_alias,
                          where=(SELFTEST, 1))
    findings = jaxpr_audit.audit_donation(entry)
    assert [f for f in findings if f.rule == "jaxpr.donation"]


def test_registry_completeness_clean():
    assert jaxpr_audit.audit_registry() == []
    discovered = set(ep.discover_public_solvers())
    assert len(discovered) >= 25
    # every discovered entry is in exactly one of the two sets
    assert discovered <= (ep.REGISTERED_FUNCS | set(ep.EXEMPT_FUNCS))
    assert not (ep.REGISTERED_FUNCS & set(ep.EXEMPT_FUNCS))


def test_registry_unregistered_flags(monkeypatch):
    victim = "gauss_tpu.core.blocked:lu_solve"
    assert victim in ep.REGISTERED_FUNCS
    monkeypatch.setattr(ep, "REGISTERED_FUNCS",
                        ep.REGISTERED_FUNCS - {victim})
    findings = jaxpr_audit.audit_registry()
    assert any(f.rule == "registry.unregistered" and f.symbol == victim
               for f in findings)


def test_registry_stale_flags(monkeypatch):
    monkeypatch.setattr(
        ep, "REGISTERED_FUNCS",
        ep.REGISTERED_FUNCS | {"gauss_tpu.core.blocked:solve_vanished"})
    findings = jaxpr_audit.audit_registry()
    assert any(f.rule == "registry.stale"
               and f.symbol.endswith("solve_vanished") for f in findings)


# -- lockset checker ---------------------------------------------------------

def _lockset_on(tmp_path, source, name="fix.py"):
    path = tmp_path / name
    path.write_text(textwrap.dedent(source))
    return lockset.run(files=[name], root=str(tmp_path))


def test_lockset_serving_core_clean():
    findings, stats = lockset.run()
    assert findings == [], [f.format() for f in findings]
    assert stats["guarded_fields"] >= 20
    assert stats["locks_taken"] >= 5


def test_lockset_nested_with_locks(tmp_path):
    findings, _ = _lockset_on(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self.a_lock = threading.Lock()
                self.b_lock = threading.Lock()
                self.x = 0   # guarded by: self.a_lock
                self.y = 0   # guarded by: self.b_lock

            def both(self):
                with self.a_lock:
                    with self.b_lock:
                        self.y += self.x
        """)
    assert findings == [], [f.format() for f in findings]


def test_lockset_released_mid_function(tmp_path):
    findings, _ = _lockset_on(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0   # guarded by: self._lock

            def leak(self):
                with self._lock:
                    self.n += 1
                return self.n
        """)
    assert _rules(findings) == {"lockset.unguarded"}
    # the access AFTER the with released the lock, not the guarded one
    assert findings[0].line == 12
    assert findings[0].symbol == "C.n"


def test_lockset_worker_thread_confinement(tmp_path):
    findings, _ = _lockset_on(tmp_path, """
        class W:
            def __init__(self):
                self.jobs = []   # owned by: pump

            # lockset: thread pump
            def on_pump(self):
                self.jobs.append(1)

            def off_pump(self):
                self.jobs.append(2)
        """)
    assert _rules(findings) == {"lockset.thread"}
    assert len(findings) == 1
    assert findings[0].line == 11


def test_lockset_never_locked_flags(tmp_path):
    findings, _ = _lockset_on(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.ghost = 0   # guarded by: self._phantom_lock

            def read(self):
                with self._lock:
                    return 1
        """)
    assert any(f.rule == "lockset.never_locked" and f.symbol == "C.ghost"
               and f.line == 7 for f in findings)


def test_lockset_holds_annotation_and_waiver(tmp_path):
    findings, _ = _lockset_on(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.k = 0   # guarded by: self._lock

            # lockset: holds self._lock
            def helper(self):
                self.k += 1

            def taker(self):
                with self._lock:
                    self.helper()

            def snapshot(self):
                return self.k   # lockset: ok — stats snapshot for test
        """)
    assert findings == [], [f.format() for f in findings]


def test_lockset_cas_terminal_patterns(tmp_path):
    findings, _ = _lockset_on(tmp_path, """
        def bad(obs, req, res):
            obs.emit("serve_request", status="ok")

        def good_if(obs, req, res):
            if req.resolve(res):
                obs.emit("serve_request", status="ok")

        def good_named(obs, req, res):
            won = req.resolve(res)
            if won:
                obs.emit("serve_request", status="ok")

        def good_early_return(obs, req, res):
            if not req.resolve(res):
                return
            obs.emit("serve_request", status="ok")

        def untracked(obs):
            obs.emit("serve_batch", size=4)
        """)
    assert [f.rule for f in findings] == ["lockset.cas_terminal"]
    assert findings[0].line == 3
    assert findings[0].symbol == "bad"


def test_selftest_fixture_every_rule_fires():
    """The seeded-violation module: every rule in EXPECTED_FINDINGS fires
    at exactly the recorded file:line when fed back via the check-file /
    check-entry surface."""
    expected = selftest.expected_findings()
    got = {}
    findings, _ = jaxpr_audit.run(
        extra_entries=selftest.selftest_entries())
    lfindings, _ = lockset.run(
        files=list(lockset.DEFAULT_FILES) + [SELFTEST])
    dfindings, _ = driftlint.run(extra_files=(SELFTEST,))
    for f in findings + lfindings + dfindings:
        got.setdefault(f.rule, set()).add((f.path, f.line))
    for rule, where in expected.items():
        assert where in got.get(rule, set()), \
            f"{rule} did not fire at {where}: {got.get(rule)}"
    # the waived read in the fixture must NOT appear
    waived_line = selftest.SelftestRacyCounter.waived_read.\
        __code__.co_firstlineno + 1
    assert (SELFTEST, waived_line) not in got.get("lockset.unguarded",
                                                 set())


# -- drift lint --------------------------------------------------------------

def test_drift_repo_clean():
    findings, stats = driftlint.run()
    assert findings == [], [f.format() for f in findings]
    assert stats["config_fields"] >= 30
    assert stats["events"] >= 30


def test_default_scan_excludes_selftest():
    files = driftlint._py_files(REPO)
    assert not any(p.endswith("selftest.py") for p in files)
    assert any(p.endswith("driftlint.py") for p in files)


def test_falsy_default_flags_and_waiver(tmp_path):
    pkg = tmp_path / "gauss_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(textwrap.dedent("""
        class Cfg:
            pass

        def f(c=None):
            return c or Cfg()

        def g(c=None):
            return c or Cfg()  # driftlint: ok — deliberate fixture
        """))
    findings = driftlint.check_falsy_default(str(tmp_path))
    assert len(findings) == 1
    assert findings[0].line == 6
    assert findings[0].symbol == "Cfg"


def test_event_doc_flags(tmp_path):
    pkg = tmp_path / "gauss_tpu"
    pkg.mkdir()
    (pkg / "emitter.py").write_text(textwrap.dedent("""
        def e(obs):
            obs.emit("documented_ev", x=1)
            obs.emit("undocumented_ev", x=1)
        """))
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "OBSERVABILITY.md").write_text("| `documented_ev` | x |\n")
    findings = driftlint.check_event_doc(str(tmp_path))
    assert [f.symbol for f in findings] == ["undocumented_ev"]
    assert findings[0].line == 4


def test_tune_source_flags(tmp_path):
    core = tmp_path / "gauss_tpu" / "core"
    core.mkdir(parents=True)
    (core / "blocked.py").write_text("CHUNK_DEFAULT = 16\n")
    findings = driftlint.check_tune_source(str(tmp_path))
    bad = [f for f in findings if f.symbol == "CHUNK_DEFAULT"]
    assert len(bad) == 1 and bad[0].rule == "drift.tune_source"
    (core / "blocked.py").write_text(
        "from gauss_tpu.tune.space import CHUNK_SEED as CHUNK_DEFAULT\n")
    findings = driftlint.check_tune_source(str(tmp_path))
    assert not [f for f in findings if f.symbol == "CHUNK_DEFAULT"]


def test_ratchet_history_flags(monkeypatch):
    from gauss_tpu.obs import regress

    assert driftlint.check_ratchet_history(REPO) == []
    monkeypatch.setitem(regress.RATCHET_BASELINES,
                        "phantom:selftest/metric", 1.0)
    findings = driftlint.check_ratchet_history(REPO)
    assert [f.symbol for f in findings] == ["phantom:selftest/metric"]


def test_api_signature_flags(tmp_path):
    kern = tmp_path / "gauss_tpu" / "kernels"
    kern.mkdir(parents=True)
    (kern / "matmul_pallas.py").write_text(textwrap.dedent("""
        def matmul_pallas(a, b, *, bm=None, bn=None, bk=None):
            return a
        """))
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "API.md").write_text(
        "| `matmul_pallas` | `(a, b, bm=512, bn=512, bk=1024)` | stale |\n")
    findings = driftlint.check_api_signature(str(tmp_path))
    assert findings and all(f.rule == "drift.api_signature"
                            for f in findings)
    (docs / "API.md").write_text(
        "| `matmul_pallas` | `(a, b, bm=None, bn=None, bk=None)` | ok |\n")
    assert driftlint.check_api_signature(str(tmp_path)) == []


# -- baseline ratchet --------------------------------------------------------

def _finding(rule="drift.falsy_default", path="x.py", line=3, symbol="C"):
    return Finding(rule=rule, path=path, line=line, symbol=symbol,
                   message="m")


def test_baseline_grandfather_and_ratchet(tmp_path):
    f1, f2 = _finding(), _finding(rule="lockset.unguarded", symbol="D.n")
    path = str(tmp_path / "baseline.json")
    counts = save_baseline([f1, f1, f2], path)
    assert counts == {f1.key: 2, f2.key: 1}
    baseline = load_baseline(path)
    # same findings: all grandfathered, no news
    new, notes = check_against_baseline([f1, f1, f2], baseline)
    assert new == [] and notes == []
    # one fixed: ratchet note tells the operator to shrink the baseline
    new, notes = check_against_baseline([f1, f2], baseline)
    assert new == [] and len(notes) == 1 and "shrink" in notes[0]
    # over budget: the extra occurrence is NEW and fails
    new, _ = check_against_baseline([f1, f1, f1, f2], baseline)
    assert len(new) == 1
    # an unseen key is always new
    new, _ = check_against_baseline([_finding(symbol="E")], baseline)
    assert len(new) == 1
    # a missing baseline file is empty
    assert load_baseline(str(tmp_path / "nope.json")) == {}


def test_finding_key_excludes_line():
    a = _finding(line=3)
    b = _finding(line=99)
    assert a.key == b.key
    assert a.format().startswith("x.py:3: [drift.falsy_default]")


# -- history / regress ingest ------------------------------------------------

def test_history_records_zero_counts():
    summary = {"kind": "lint_report", "run_id": "abc",
               "passes": {"jaxpr": {"findings": 0},
                          "lockset": {"findings": 0},
                          "drift": {"findings": 2}},
               "findings_total": 2}
    recs = history_records(summary)
    by_metric = {r["metric"]: r["value"] for r in recs}
    assert by_metric == {"lint:jaxpr/findings": 0.0,
                         "lint:lockset/findings": 0.0,
                         "lint:drift/findings": 2.0,
                         "lint:findings_total": 2.0}
    assert all(r["kind"] == "lint" for r in recs)


def test_regress_ingests_lint_report(tmp_path):
    from gauss_tpu.obs import regress

    path = tmp_path / "lint.json"
    path.write_text(json.dumps(
        {"kind": "lint_report", "run_id": "xyz",
         "passes": {"jaxpr": {"findings": 0}}, "findings_total": 0}))
    recs = regress.ingest_file(str(path))
    assert {r["metric"] for r in recs} == {"lint:jaxpr/findings",
                                           "lint:findings_total"}
    # the committed epochs hold 0 per pass, so 0 is in-band and any
    # finding count is out-of-band
    verdicts = regress.check_records(
        recs, regress.load_history(os.path.join(REPO, "reports",
                                                "history.jsonl")))
    # 0 matches the committed epochs' median exactly: "fast" (at or
    # below baseline) is the green verdict here, never out-of-band
    assert all(v["status"] in ("ok", "fast") for v in verdicts)
    bad = [{**r, "value": 3.0} for r in recs]
    verdicts = regress.check_records(
        bad, regress.load_history(os.path.join(REPO, "reports",
                                               "history.jsonl")))
    assert any(v["status"] == "out-of-band" for v in verdicts)


# -- CLI ---------------------------------------------------------------------

def test_cli_clean_on_repo(tmp_path, capsys):
    """The green half of the acceptance criteria: exit 0 on this repo
    with the committed EMPTY baseline, all three passes, regress-gated."""
    out_json = str(tmp_path / "lint.json")
    rc = cli.main(["--json", out_json, "--regress-check"])
    assert rc == 0
    summary = json.load(open(out_json))
    assert summary["kind"] == "lint_report"
    assert summary["clean"] is True
    assert summary["new_findings"] == 0
    assert set(summary["passes"]) == {"jaxpr", "lockset", "drift"}
    assert all(p["findings"] == 0 for p in summary["passes"].values())
    assert "clean" in capsys.readouterr().out


def test_cli_seeded_violations_fail_with_location(capsys):
    """The red half: the fixture module through --check-file /
    --check-entry exits nonzero, and every expected rule prints at its
    exact file:line."""
    rc = cli.main(["--check-file", SELFTEST,
                   "--check-entry", SELFTEST_SPEC])
    out = capsys.readouterr().out
    assert rc == 1
    for rule, (path, line) in selftest.expected_findings().items():
        assert f"{path}:{line}: [{rule}]" in out, (rule, path, line)
    assert "new finding(s)" in out


def test_cli_baseline_grandfather_flow(tmp_path, capsys):
    """--update-baseline grandfathers current findings; a rerun is green
    against that baseline; fixing them all leaves ratchet notes. (jaxpr
    pass skipped: the lockset+drift fixtures are enough surface and keep
    this seconds, not a second registry trace.)"""
    baseline = str(tmp_path / "baseline.json")
    args = ["--passes", "lockset,drift", "--check-file", SELFTEST,
            "--baseline", baseline]
    assert cli.main(args) == 1
    assert cli.main(args + ["--update-baseline"]) == 0
    capsys.readouterr()
    assert cli.main(args) == 0
    assert "(grandfathered)" in capsys.readouterr().out
    # all fixed (no check-file): green, with shrink-the-baseline notes
    rc = cli.main(["--passes", "lockset,drift", "--baseline", baseline])
    out = capsys.readouterr().out
    assert rc == 0
    assert "shrink the baseline" in out


def test_cli_unknown_pass_errors():
    with pytest.raises(SystemExit):
        cli.main(["--passes", "jaxpr,telepathy"])


def test_committed_baseline_is_empty():
    from gauss_tpu.analysis import default_baseline_path

    assert load_baseline(default_baseline_path()) == {}
