"""Edge cases surfaced by review: bounds, lossless roundtrip, engine errors."""

import io

import numpy as np
import pytest

from gauss_tpu.io import datfile


def test_zero_coordinate_rejected():
    """'0 3 5' is not a terminator (needs both zero) and must not wrap to -1."""
    with pytest.raises(ValueError, match="out of bounds"):
        datfile.read_dat(io.StringIO("3 3 1\n0 3 5.0\n0 0 0\n"))


def test_out_of_range_coordinate_rejected():
    with pytest.raises(ValueError, match="out of bounds"):
        datfile.read_dat(io.StringIO("3 3 1\n4 1 5.0\n0 0 0\n"))


def test_roundtrip_exact(tmp_path):
    rng = np.random.default_rng(7)
    a = rng.standard_normal((9, 9))
    p = tmp_path / "exact.dat"
    datfile.write_dat(p, a)
    back = datfile.read_dat_dense(p, engine="python")
    np.testing.assert_array_equal(back, a)


def test_native_engine_requires_path():
    with pytest.raises(ValueError, match="file path"):
        datfile.read_dat_dense(io.StringIO("1 1 1\n1 1 2\n"), engine="native")


def test_malformed_body_line_raises_valueerror():
    """Short or garbage body lines raise ValueError (not IndexError), so the
    CLI's error handling catches them."""
    with pytest.raises(ValueError, match="malformed"):
        datfile.read_dat(io.StringIO("3 3 1\n1 2\n0 0 0\n"))
    with pytest.raises(ValueError, match="malformed"):
        datfile.read_dat(io.StringIO("3 3 1\nx y z\n0 0 0\n"))
