"""Edge cases surfaced by review: bounds, lossless roundtrip, engine errors."""

import io

import numpy as np
import pytest

from gauss_tpu.io import datfile


def test_zero_coordinate_rejected():
    """'0 3 5' is not a terminator (needs both zero) and must not wrap to -1."""
    with pytest.raises(ValueError, match="out of bounds"):
        datfile.read_dat(io.StringIO("3 3 1\n0 3 5.0\n0 0 0\n"))


def test_out_of_range_coordinate_rejected():
    with pytest.raises(ValueError, match="out of bounds"):
        datfile.read_dat(io.StringIO("3 3 1\n4 1 5.0\n0 0 0\n"))


def test_roundtrip_exact(tmp_path):
    rng = np.random.default_rng(7)
    a = rng.standard_normal((9, 9))
    p = tmp_path / "exact.dat"
    datfile.write_dat(p, a)
    back = datfile.read_dat_dense(p, engine="python")
    np.testing.assert_array_equal(back, a)


def test_native_engine_requires_path():
    with pytest.raises(ValueError, match="file path"):
        datfile.read_dat_dense(io.StringIO("1 1 1\n1 1 2\n"), engine="native")


def test_malformed_body_line_raises_valueerror():
    """Short or garbage body lines raise ValueError (not IndexError), so the
    CLI's error handling catches them."""
    with pytest.raises(ValueError, match="malformed"):
        datfile.read_dat(io.StringIO("3 3 1\n1 2\n0 0 0\n"))
    with pytest.raises(ValueError, match="malformed"):
        datfile.read_dat(io.StringIO("3 3 1\nx y z\n0 0 0\n"))


# -- strict-mode hardening (resilience PR): typed errors with line numbers --

def test_nan_inf_values_rejected_with_line_number():
    """float() happily parses 'nan'/'inf' (so does the reference's fscanf);
    strict mode must refuse them before they poison a solve."""
    with pytest.raises(datfile.DatFormatError, match="non-finite") as ei:
        datfile.read_dat(io.StringIO("2 2 2\n1 1 1.0\n2 2 nan\n0 0 0\n"))
    assert ei.value.line == 3
    with pytest.raises(datfile.DatFormatError, match="non-finite") as ei:
        datfile.read_dat(io.StringIO("2 2 1\n1 2 -inf\n0 0 0\n"))
    assert ei.value.line == 2
    # strict=False keeps reference fscanf parity.
    _, _, _, vals = datfile.read_dat(
        io.StringIO("2 2 1\n1 2 inf\n0 0 0\n"), strict=False)
    assert np.isinf(vals[0])


def test_duplicate_entry_error_names_both_lines():
    with pytest.raises(datfile.DatFormatError, match="first at line 2") as ei:
        datfile.read_dat(io.StringIO("3 3 3\n2 1 5\n1 1 1\n2 1 7\n0 0 0\n"))
    assert ei.value.line == 4


def test_missing_terminator_line_number_and_escape():
    with pytest.raises(datfile.DatFormatError, match="terminator") as ei:
        datfile.read_dat(io.StringIO("2 2 1\n1 1 3.5\n"))
    assert ei.value.line == 2
    n, rows, cols, vals = datfile.read_dat(io.StringIO("2 2 1\n1 1 3.5\n"),
                                           strict=False)
    assert n == 2 and vals[0] == 3.5


def test_malformed_header_is_typed_with_line_one():
    with pytest.raises(datfile.DatFormatError) as ei:
        datfile.read_dat(io.StringIO("2 x 1\n1 1 3.5\n0 0 0\n"))
    assert ei.value.line == 1
    with pytest.raises(datfile.DatFormatError) as ei:
        datfile.read_dat(io.StringIO("-2 -2 1\n1 1 3.5\n0 0 0\n"))
    assert ei.value.line == 1


def test_datformaterror_is_valueerror():
    """Pre-existing `except ValueError` call sites (the CLIs) keep catching
    the new typed errors."""
    assert issubclass(datfile.DatFormatError, ValueError)
    err = datfile.DatFormatError("boom", line=7)
    assert "line 7" in str(err) and err.line == 7


def test_strict_roundtrip_unaffected(tmp_path):
    """write_dat output (terminated, duplicate-free, finite) parses clean
    under the strict default."""
    rng = np.random.default_rng(3)
    a = rng.standard_normal((6, 6))
    p = tmp_path / "clean.dat"
    datfile.write_dat(p, a)
    np.testing.assert_array_equal(
        datfile.read_dat_dense(p, engine="python"), a)
