"""Pallas kernel tests (CPU interpreter mode via conftest's cpu backend)."""

import numpy as np
import pytest

import jax.numpy as jnp

from gauss_tpu.kernels.matmul_pallas import matmul_pallas
from gauss_tpu.kernels.rowelim_pallas import eliminate_step_pallas, gauss_solve_rowelim
from gauss_tpu.core.gauss import eliminate
from gauss_tpu.io import synthetic
from gauss_tpu.verify import checks


@pytest.mark.parametrize("shape", [(64, 64, 64), (128, 256, 192), (100, 70, 50)])
def test_matmul_pallas_matches_numpy(rng, shape):
    m, k, n = shape
    a = rng.standard_normal((m, k)).astype(np.float32)
    b = rng.standard_normal((k, n)).astype(np.float32)
    c = np.asarray(matmul_pallas(a, b, bm=64, bn=128, bk=128))
    ref = a.astype(np.float64) @ b.astype(np.float64)
    np.testing.assert_allclose(c, ref, rtol=1e-4, atol=1e-4 * np.abs(ref).max())


def test_matmul_pallas_cuda_inputs():
    """The reference's CUDA input pattern at small n."""
    n = 64
    idx = np.arange(n * n, dtype=np.float64)
    a = (idx + 1).reshape(n, n).astype(np.float32)
    b = (1.0 / (idx + 1)).reshape(n, n).astype(np.float32)
    c = np.asarray(matmul_pallas(a, b, bm=64, bn=128, bk=128))
    ref = a.astype(np.float64) @ b.astype(np.float64)
    assert checks.elementwise_match(c, ref, epsilon=checks.EPSILON * np.abs(ref).max())


def test_matmul_pallas_bad_shapes():
    with pytest.raises(ValueError):
        matmul_pallas(np.ones((4, 5), np.float32), np.ones((4, 5), np.float32))


def test_eliminate_step_matches_core(rng):
    """One kernel step == one step of the core oracle's rank-1 update."""
    n = 128
    a = (rng.standard_normal((n, n)) + n * np.eye(n)).astype(np.float32)
    b = rng.standard_normal(n).astype(np.float32)
    # Augment: kernel works on [A | b | pad].
    m = np.zeros((n, n + 128), np.float32)
    m[:, :n] = a
    m[:, n] = b
    out = np.asarray(eliminate_step_pallas(m, 0, bm=64, bn=128))
    # Expected: scale row 0, eliminate below (diag dominant => no swap at i=0).
    exp = m.astype(np.float64).copy()
    exp[0] /= exp[0, 0]
    for j in range(1, n):
        exp[j] -= exp[j, 0] * exp[0]
    np.testing.assert_allclose(out[:, : n + 1], exp[:, : n + 1], rtol=2e-5,
                               atol=2e-4 * np.abs(exp).max())
    assert out[0, 0] == 1.0
    assert np.all(out[1:, 0] == 0.0)


@pytest.mark.parametrize("n", [32, 100, 128])
def test_gauss_solve_rowelim(rng, n):
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal(n).astype(np.float32)
    x = np.asarray(gauss_solve_rowelim(a, b, bm=32, bn=128), np.float64)
    ref = np.linalg.solve(a.astype(np.float64), b.astype(np.float64))
    np.testing.assert_allclose(x, ref, rtol=5e-3, atol=5e-3)


def test_gauss_solve_rowelim_internal_pattern():
    n = 96
    a = synthetic.internal_matrix(n, dtype=np.float32)
    b = synthetic.internal_rhs(n, dtype=np.float32)
    x = np.asarray(gauss_solve_rowelim(a, b, bm=32, bn=128), np.float64)
    assert checks.internal_pattern_ok(x, atol=1e-4)


def test_rowelim_matches_unblocked_eliminate(rng):
    """Full U from chained kernel steps == core eliminate's U (same policy)."""
    n = 64
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal(n).astype(np.float32)
    x_kernel = np.asarray(gauss_solve_rowelim(a, b, bm=32, bn=128))
    res = eliminate(a, b, pivoting="partial")
    from gauss_tpu.core.gauss import back_substitute

    x_core = np.asarray(back_substitute(res.u, res.y))
    # f32 paths with different accumulation orders; equality is to f32 noise.
    np.testing.assert_allclose(x_kernel, x_core, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("n", [64, 100, 128])
def test_panel_pallas_blocked_lu(rng, n):
    """Blocked LU with the Pallas panel kernel (interpret mode) == numpy."""
    from gauss_tpu.core.blocked import gauss_solve_blocked

    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal(n).astype(np.float32)
    x = np.asarray(gauss_solve_blocked(a, b, panel=32, panel_impl="pallas"),
                   np.float64)
    ref = np.linalg.solve(a.astype(np.float64), b.astype(np.float64))
    np.testing.assert_allclose(x, ref, rtol=5e-3, atol=5e-3)


def test_panel_pallas_matches_jax_panel(rng):
    """Same factors from both panel implementations: identical pivots
    always; values to f32 accumulation noise (the two-level deferred form
    applies each sub-panel's eliminations to the rest of the panel as one
    rank-seg dot, a reordering of the same exact-arithmetic updates — its
    accuracy vs f64 is the same as the classic form's, verified in
    test_panel_defer_accuracy)."""
    from gauss_tpu.core.blocked import lu_factor_blocked

    n = 96
    a = rng.standard_normal((n, n)).astype(np.float32)
    f_jax = lu_factor_blocked(a, panel=32, panel_impl="jax")
    f_pl = lu_factor_blocked(a, panel=32, panel_impl="pallas")
    np.testing.assert_array_equal(np.asarray(f_jax.perm), np.asarray(f_pl.perm))
    np.testing.assert_allclose(np.asarray(f_jax.m), np.asarray(f_pl.m),
                               rtol=2e-3, atol=2e-3)


def test_panel_defer_accuracy(rng):
    """The deferred (two-level) panel form must match an f64 elimination of
    the same column block as closely as the classic per-step form does —
    identical pivot sequences, comparable max relative error — and both
    forms must agree with each other to f32 reordering noise."""
    from gauss_tpu.kernels.panel_pallas import panel_factor_pallas

    h, panel = 200, 64
    p = rng.standard_normal((h, panel)).astype(np.float32)

    p64 = p.astype(np.float64)
    live = np.ones(h, bool)
    order = []
    for j in range(panel):
        c = np.where(live, np.abs(p64[:, j]), -np.inf)
        pi = int(np.argmax(c))
        order.append(pi)
        live[pi] = False
        piv = p64[pi, j]
        mult = np.where(live, p64[:, j] / piv, 0.0)
        p64[:, j] = np.where(live, mult, p64[:, j])
        for t in range(j + 1, panel):
            p64[:, t] -= mult * p64[pi, t]

    errs = {}
    for defer, seg in ((False, 16), (True, 16), (True, 32)):
        out, ipiv, perm, mp = panel_factor_pallas(p, 0, defer=defer, seg=seg)
        assert list(np.asarray(ipiv)) == order
        ref = p64[np.asarray(perm)]
        errs[(defer, seg)] = float(np.max(
            np.abs(np.asarray(out) - ref) / (np.abs(ref) + 1e-6)))
    # Same accuracy class: deferred within 4x of classic (measured ~1x on
    # TPU interpret under jax 0.6, 3.3x under the 0.4-series CPU dot
    # ordering — the bound is a class check, not a bit-accuracy contract).
    assert errs[(True, 16)] <= 4 * max(errs[(False, 16)], 1e-5)
    assert errs[(True, 32)] <= 4 * max(errs[(False, 16)], 1e-5)


def test_panel_defer_singular_reports_zero_pivot():
    """A rank-deficient column block through the DEFERRED form still reports
    min_abs_pivot == 0 (the singular-abort signal every engine keys on);
    the deferred rank-seg dots must not mask the classic form's policy."""
    from gauss_tpu.kernels.panel_pallas import panel_factor_pallas

    h, panel = 96, 48
    p = np.ones((h, panel), np.float32)  # rank 1: step 2 meets a zero pivot
    for defer in (False, True):
        out, ipiv, perm, mp = panel_factor_pallas(p, 0, defer=defer,
                                                  seg=16 if defer else None)
        assert float(mp) == 0.0, defer


def test_defer_seg_policy():
    """defer_seg: 0 past panel_fits_vmem or past the transient-inclusive
    budget (the h=4096/panel=256 chip OOM of round 5); 32 where the
    deferred form measured fastest; narrower only for narrow panels."""
    from gauss_tpu.kernels.panel_pallas import (DEFER_WORKSET_FACTOR,
                                                defer_seg)
    from gauss_tpu.core.blocked import PANEL_VMEM_BUDGET

    assert defer_seg(2048, 256) == 32
    assert defer_seg(4096, 256) == 0      # the observed chip OOM config
    assert defer_seg(2048, 32) == 16
    assert defer_seg(2048, 16) == 0       # no sub-panel narrower than 16
    assert defer_seg(65536, 128) == 0     # past panel_fits_vmem entirely
    # The budget rule itself, at the boundary.
    h_edge = PANEL_VMEM_BUDGET // (128 * 4 * DEFER_WORKSET_FACTOR)
    assert defer_seg(h_edge, 128) in (0, 32)
    assert defer_seg(h_edge * 2, 128) == 0


@pytest.mark.parametrize("shape", [(64, 64, 64), (100, 70, 130)])
def test_matmul_pallas_stripe(rng, shape):
    from gauss_tpu.kernels.matmul_pallas import matmul_pallas_stripe

    m, k, n = shape
    a = rng.standard_normal((m, k)).astype(np.float32)
    b = rng.standard_normal((k, n)).astype(np.float32)
    c = np.asarray(matmul_pallas_stripe(a, b, bm=64, bk=128))
    ref = a.astype(np.float64) @ b.astype(np.float64)
    np.testing.assert_allclose(c, ref, rtol=1e-4, atol=1e-4 * np.abs(ref).max())


def test_stripe_matches_tiled_variant(rng):
    from gauss_tpu.kernels.matmul_pallas import matmul_pallas, matmul_pallas_stripe

    a = rng.standard_normal((96, 96)).astype(np.float32)
    b = rng.standard_normal((96, 96)).astype(np.float32)
    # Pinned to "highest": this checks the two tilings compute the same
    # product; under the default bf16x3 the tilings' different accumulation
    # orders would only agree to ~1e-3.
    c1 = np.asarray(matmul_pallas(a, b, bm=32, bn=128, bk=128,
                                  precision="highest"))
    c2 = np.asarray(matmul_pallas_stripe(a, b, bm=32, bk=128,
                                         precision="highest"))
    np.testing.assert_allclose(c1, c2, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("seg", [8, 16, 32])
def test_panel_pallas_segmented_matches_single_segment(rng, seg):
    """The trace-time segmented step loop (seg < panel) is bit-identical to
    the single-segment (seg == panel) kernel — including an unaligned seg."""
    from gauss_tpu.kernels.panel_pallas import panel_factor_pallas

    h, panel = 96, 48
    p = rng.standard_normal((h, panel)).astype(np.float32)
    out1, ipiv1, perm1, mp1 = panel_factor_pallas(p, 16, seg=panel)
    out2, ipiv2, perm2, mp2 = panel_factor_pallas(p, 16, seg=seg)
    np.testing.assert_array_equal(np.asarray(ipiv1), np.asarray(ipiv2))
    np.testing.assert_array_equal(np.asarray(perm1), np.asarray(perm2))
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    assert float(mp1) == float(mp2)


def test_panel_pallas_rejects_bad_seg():
    from gauss_tpu.kernels.panel_pallas import panel_factor_pallas

    p = np.eye(8, dtype=np.float32)
    with pytest.raises(ValueError):
        panel_factor_pallas(p, 0, seg=0)
    with pytest.raises(ValueError):
        panel_factor_pallas(p, 0, seg=-4)


def test_stripe_blocks_fit_vmem_budget():
    """n=2048 at default blocks used to exceed the 16 MB VMEM budget
    (compile-time OOM on v5e); the sizing must shrink blocks to fit."""
    from gauss_tpu.kernels.matmul_pallas import (
        STRIPE_VMEM_BUDGET, _stripe_blocks, _stripe_vmem_bytes)

    for n in (256, 1001, 2048, 4096):
        bm, bk = _stripe_blocks(n, n, n, 256, 512, 4)
        assert _stripe_vmem_bytes(bm, bk, -(-n // 128) * 128, 4) <= STRIPE_VMEM_BUDGET
    with pytest.raises(ValueError, match="matmul_pallas"):
        _stripe_blocks(32768, 32768, 32768, 256, 512, 4)


def test_stripe_shrunk_blocks_correct(rng):
    """The shrunken-block path computes the same product (interpret mode)."""
    from gauss_tpu.kernels.matmul_pallas import matmul_pallas_stripe

    a = rng.standard_normal((96, 80)).astype(np.float32)
    b = rng.standard_normal((80, 160)).astype(np.float32)
    c = np.asarray(matmul_pallas_stripe(a, b, bm=32, bk=128,
                                        precision="highest"))
    np.testing.assert_allclose(
        c, a.astype(np.float64) @ b.astype(np.float64), rtol=1e-5, atol=1e-4)


def test_matmul_pallas_bf16x3_meets_comparator(rng):
    """The manual in-kernel bf16x3 path (the "high" default, VERDICT r3
    next #3) must pass the reference's eps=1e-4 comparator (scaled, as the
    CLI applies it) on both kernels, and must clearly beat a single bf16
    pass; "highest" stays available and tighter."""
    from gauss_tpu.kernels.matmul_pallas import (matmul_pallas,
                                                 matmul_pallas_stripe)

    m, k, n = 128, 512, 256
    a = rng.standard_normal((m, k)).astype(np.float32)
    b = rng.standard_normal((k, n)).astype(np.float32)
    ref = a.astype(np.float64) @ b.astype(np.float64)
    scale = np.abs(ref).max()
    c_high = np.asarray(matmul_pallas(a, b, precision="high"))
    assert checks.elementwise_match(c_high, ref,
                                    epsilon=checks.EPSILON * scale)
    c_stripe = np.asarray(matmul_pallas_stripe(a, b, precision="high"))
    assert checks.elementwise_match(c_stripe, ref,
                                    epsilon=checks.EPSILON * scale)
    # A lone bf16 pass loses the low mantissa bits the x3 scheme recovers.
    import jax.numpy as jnp

    a16 = jnp.asarray(a).astype(jnp.bfloat16).astype(jnp.float32)
    b16 = jnp.asarray(b).astype(jnp.bfloat16).astype(jnp.float32)
    c_bf16 = np.asarray(jnp.dot(a16, b16), np.float64)
    err_high = np.abs(c_high - ref).max()
    err_bf16 = np.abs(c_bf16 - ref).max()
    assert err_high < err_bf16 / 10
    c_highest = np.asarray(matmul_pallas(a, b, precision="highest"))
    assert np.abs(c_highest - ref).max() <= err_high


@pytest.mark.parametrize("n,k", [(32, 8), (100, 16), (200, 32)])
def test_gauss_solve_rowelim_batched(rng, n, k):
    """The batched (k steps per launch) form must match numpy on systems
    where pivoting matters, with the same verification bar as per-step."""
    from gauss_tpu.kernels.rowelim_pallas import gauss_solve_rowelim_batched

    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal(n).astype(np.float32)
    x = np.asarray(gauss_solve_rowelim_batched(a, b, k=k, bm=32, bn=64),
                   np.float64)
    ref = np.linalg.solve(a.astype(np.float64), b.astype(np.float64))
    np.testing.assert_allclose(x, ref, rtol=5e-3, atol=5e-3)


def test_rowelim_batched_scan_substitution(rng):
    """Above ROWELIM_UNROLL_MAX_NB blocks the back-substitution runs as one
    lax.scan (VERDICT r3 weak #4 — the unrolled chain's trace payload kept
    the engine out of the 16384 cell); it must agree with the unrolled form
    at an nb just past the threshold."""
    from gauss_tpu.kernels import rowelim_pallas as rp

    k = 8
    n = k * (rp.ROWELIM_UNROLL_MAX_NB + 3)  # nb > threshold -> scan form
    a = (rng.standard_normal((n, n)) + n * np.eye(n)).astype(np.float32)
    b = rng.standard_normal(n).astype(np.float32)
    x = np.asarray(rp.gauss_solve_rowelim_batched(a, b, k=k, bm=8, bn=64),
                   np.float64)
    ref = np.linalg.solve(a.astype(np.float64), b.astype(np.float64))
    np.testing.assert_allclose(x, ref, rtol=5e-3, atol=5e-3)


def test_rowelim_explicit_pallas_past_vmem_ceiling_raises(monkeypatch):
    """An explicit panel_impl='pallas' past the VMEM ceiling must fail with
    a clear sizing error, not a Mosaic VMEM error (ADVICE r3); 'auto'
    resolves to the stock-JAX panel there instead. The check lives in
    _resolve_panel_impl, shared with every core.blocked entry, and applies
    only on a real TPU (interpret mode has no VMEM limit)."""
    import jax

    from gauss_tpu.core import blocked
    from gauss_tpu.kernels import rowelim_pallas as rp

    # Shrink the budget so a tiny system is "past the ceiling" — the real
    # ceiling needs n ~ 60k, unaffordable in a unit test — and fake a TPU
    # backend (the raise is trace-time, before any Mosaic lowering).
    monkeypatch.setattr(blocked, "PANEL_VMEM_BUDGET", 1024)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    a = np.eye(64, dtype=np.float32)
    b = np.zeros(64, dtype=np.float32)
    with pytest.raises(ValueError, match="VMEM budget"):
        rp.gauss_solve_rowelim_batched(a, b, k=16, bm=16, bn=64,
                                       panel_impl="pallas")


def test_auto_rowelim_k_never_implies_unapproved_launch(monkeypatch):
    """auto_rowelim_k must always return a k that either fits the VMEM
    model (Pallas launch approved) or that the engine's shared panel-impl
    resolution routes to the stock-JAX panel — never a k implying a Pallas
    launch panel_fits_vmem has not approved (ADVICE r3 #2 / VERDICT r4
    weak #3). With the round-5 aliased kernel this holds to absurd sizes;
    the fallback behavior is preserved under a shrunk budget."""
    import jax

    from gauss_tpu.core import blocked
    from gauss_tpu.kernels.rowelim_pallas import auto_rowelim_k

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    for n in (2048, 16384, 65536):
        k = auto_rowelim_k(n)
        assert blocked.panel_fits_vmem(n, k) or \
            blocked._resolve_panel_impl("auto", n, k) == "jax"
    # Shrink the budget so nothing fits: the fallback must be the WIDEST k
    # (fewest serial groups on the no-ceiling stock-JAX path), routed jax.
    monkeypatch.setattr(blocked, "PANEL_VMEM_BUDGET", 1024)
    k = auto_rowelim_k(4096)
    assert k == 256
    assert blocked._resolve_panel_impl("auto", 4096, k) == "jax"


def test_rowelim_batched_matches_per_step(rng):
    """Batched and per-step forms implement the same engine: same pivoting
    policy, agreement to f32 accumulation noise."""
    from gauss_tpu.kernels.rowelim_pallas import gauss_solve_rowelim_batched

    n = 96
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal(n).astype(np.float32)
    xb = np.asarray(gauss_solve_rowelim_batched(a, b, k=16, bm=32, bn=64))
    xs = np.asarray(gauss_solve_rowelim(a, b, bm=32, bn=128))
    np.testing.assert_allclose(xb, xs, rtol=1e-3, atol=1e-3)


def test_rowelim_batched_internal_pattern():
    from gauss_tpu.kernels.rowelim_pallas import gauss_solve_rowelim_batched

    n = 96
    a = synthetic.internal_matrix(n, dtype=np.float32)
    b = synthetic.internal_rhs(n, dtype=np.float32)
    x = np.asarray(gauss_solve_rowelim_batched(a, b, k=16, bm=32, bn=64),
                   np.float64)
    assert checks.internal_pattern_ok(x, atol=1e-4)


def test_auto_rowelim_k_policy():
    """k resolution: 256 while the in-kernel panel block fits VMEM (the
    measured round-3 winner at every bench size), narrowing beyond."""
    from gauss_tpu.kernels.rowelim_pallas import auto_rowelim_k

    assert auto_rowelim_k(512) == 256
    assert auto_rowelim_k(2048) == 256
    assert auto_rowelim_k(8192) == 256
    assert auto_rowelim_k(16384) == 128   # 256-block no longer fits VMEM
    # Round 5: the aliased kernel made 64 a real rung (ceiling ~37k, past
    # 128's ~23k) — in-kernel pivoting continues to the HBM ceiling.
    assert auto_rowelim_k(24576) == 64
    assert auto_rowelim_k(34048) == 64
    # Nothing fits only at academic sizes; the widest k falls back and the
    # impl resolution routes it to the stock-JAX panel.
    assert auto_rowelim_k(65536) == 256


def test_rowelim_batched_auto_k(rng):
    """k=None (the default) must resolve and solve correctly."""
    from gauss_tpu.kernels.rowelim_pallas import gauss_solve_rowelim_batched

    n = 100
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal(n).astype(np.float32)
    x = np.asarray(gauss_solve_rowelim_batched(a, b), np.float64)
    ref = np.linalg.solve(a.astype(np.float64), b.astype(np.float64))
    np.testing.assert_allclose(x, ref, rtol=5e-3, atol=5e-3)


def test_rowelim_batched_zero_diagonal(rng):
    from gauss_tpu.kernels.rowelim_pallas import gauss_solve_rowelim_batched

    n = 64
    a = rng.standard_normal((n, n))
    np.fill_diagonal(a, 0.0)
    x_true = rng.standard_normal(n)
    b = a @ x_true
    x = np.asarray(gauss_solve_rowelim_batched(
        jnp.asarray(a), jnp.asarray(b), k=16, bm=32, bn=64))
    assert checks.max_rel_error(x, x_true) < 1e-8
