"""Test harness config: CPU JAX with 8 virtual devices, float64 enabled.

The reference validated its distributed path only on a real 6-node cluster
(SURVEY.md §4.5); we instead make multi-chip sharding unit-testable by forcing
8 virtual host devices, as the build plan prescribes (SURVEY.md §4 implication).
Must run before the first ``import jax`` anywhere in the test process.
"""

import os

# Hard override: the environment may pin JAX_PLATFORMS to a tunneled TPU
# ('axon'); tests must run on local CPU with virtual devices.
os.environ["JAX_PLATFORMS"] = os.environ.get("GAUSS_TPU_TEST_PLATFORM", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The env var alone is not enough: the image's sitecustomize pins the tunneled
# TPU platform ('axon'); the config update takes precedence (backend init is
# lazy, so doing this before any jax.devices() call is sufficient).
jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(258458)  # CSC 258/458, the reference's course


@pytest.fixture(params=[16, 33, 64])
def n_small(request):
    return request.param
