"""Oracle-layer tests for gauss_tpu.core.gauss.

Mirrors the reference's verification strategy (SURVEY.md §4): the internal
VERIFY pattern, the external manufactured-solution oracle, plus modern
cross-checks against numpy.linalg.solve that the reference lacked.
"""

import numpy as np
import pytest

from gauss_tpu.core.gauss import eliminate, back_substitute, gauss_solve
from gauss_tpu.io import synthetic
from gauss_tpu.verify import checks


def test_internal_pattern(n_small):
    """The internal benchmark system solves to (-0.5, 0, ..., 0, 0.5)."""
    n = n_small
    a = synthetic.internal_matrix(n)
    b = synthetic.internal_rhs(n)
    x = np.asarray(gauss_solve(a, b, pivoting="first_nonzero"))
    assert checks.internal_pattern_ok(x, atol=1e-8)


def test_partial_pivot_matches_numpy(rng, n_small):
    n = n_small
    a = rng.standard_normal((n, n))
    b = rng.standard_normal(n)
    x = np.asarray(gauss_solve(a, b, pivoting="partial"))
    expected = np.linalg.solve(a, b)
    np.testing.assert_allclose(x, expected, rtol=1e-9, atol=1e-9)


def test_manufactured_solution_oracle(rng):
    """External flavor: RHS manufactured from X__[i] = i+1; check max rel error."""
    n = 64
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    x_true = synthetic.manufactured_solution(n)
    b = synthetic.manufactured_rhs(a, x_true)
    x = np.asarray(gauss_solve(a, b, pivoting="partial"))
    assert checks.max_rel_error(x, x_true) < 1e-10


def test_zero_diagonal_first_nonzero_policy():
    """first_nonzero pivoting handles an exactly-zero diagonal via row swap."""
    a = np.array([[0.0, 2.0, 1.0],
                  [1.0, 0.0, 3.0],
                  [2.0, 1.0, 0.0]])
    b = np.array([1.0, 2.0, 3.0])
    x = np.asarray(gauss_solve(a, b, pivoting="first_nonzero"))
    np.testing.assert_allclose(x, np.linalg.solve(a, b), rtol=1e-12, atol=1e-12)


def test_perm_tracks_swaps():
    a = np.array([[0.0, 1.0], [1.0, 0.0]])
    b = np.array([3.0, 4.0])
    res = eliminate(a, b, pivoting="first_nonzero")
    # Row 1 must have been swapped into position 0.
    assert list(np.asarray(res.perm)) == [1, 0]
    x = np.asarray(back_substitute(res.u, res.y))
    np.testing.assert_allclose(x, [4.0, 3.0])


def test_unit_diagonal_and_exact_lower_zeros(rng):
    """Pivot rows are scaled (reference getPivot semantics) and the
    subdiagonal is eliminated to exact zeros."""
    n = 24
    a = rng.standard_normal((n, n))
    b = rng.standard_normal(n)
    res = eliminate(a, b, pivoting="partial")
    u = np.asarray(res.u)
    np.testing.assert_allclose(np.diag(u), np.ones(n), rtol=0, atol=0)
    assert np.all(np.tril(u, -1) == 0.0)


def test_min_abs_pivot_flags_singularity():
    a = np.array([[1.0, 2.0], [2.0, 4.0]])  # rank 1
    b = np.array([1.0, 2.0])
    res = eliminate(a, b, pivoting="partial")
    assert float(res.min_abs_pivot) < 1e-12


def test_residual_norm_acceptance(rng):
    """BASELINE.json acceptance bar: residual below 1e-4 (f64 oracle easily)."""
    n = 128
    a = synthetic.internal_matrix(n)
    b = synthetic.internal_rhs(n)
    x = np.asarray(gauss_solve(a, b))
    assert checks.residual_norm(a, x, b) < 1e-6


def test_float32_path(rng):
    """f32 inputs stay f32 (the TPU dtype) and still solve accurately."""
    n = 48
    a = (rng.standard_normal((n, n)) + n * np.eye(n)).astype(np.float32)
    b = rng.standard_normal(n).astype(np.float32)
    x = gauss_solve(a, b)
    assert x.dtype == np.float32
    np.testing.assert_allclose(
        np.asarray(x), np.linalg.solve(a.astype(np.float64), b.astype(np.float64)),
        rtol=1e-4, atol=1e-4)


def test_bad_pivoting_name():
    a = np.eye(2)
    b = np.ones(2)
    with pytest.raises(ValueError):
        gauss_solve(a, b, pivoting="bogus")
