import numpy as np

from gauss_tpu.core.matmul import matmul
from gauss_tpu.verify import checks


def test_matmul_matches_numpy(rng):
    a = rng.standard_normal((64, 48))
    b = rng.standard_normal((48, 32))
    c = np.asarray(matmul(a, b))
    np.testing.assert_allclose(c, a @ b, rtol=1e-10)


def test_matmul_f32_epsilon(rng):
    """The CUDA verify() bar: agree with the f64 product within eps=1e-4."""
    n = 256
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal((n, n)).astype(np.float32)
    c = np.asarray(matmul(a, b))
    ref = a.astype(np.float64) @ b.astype(np.float64)
    assert checks.elementwise_match(c, ref, epsilon=checks.EPSILON * np.abs(ref).max())


def test_cuda_input_pattern():
    """Reference inputs A[idx]=idx+1, B[idx]=1/(idx+1) (cuda_matmul.cu:128-134)."""
    n = 32
    idx = np.arange(n * n, dtype=np.float64)
    a = (idx + 1).reshape(n, n)
    b = (1.0 / (idx + 1)).reshape(n, n)
    c = np.asarray(matmul(a, b))
    np.testing.assert_allclose(c, a @ b, rtol=1e-12)
