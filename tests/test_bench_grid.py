"""Benchmark grid harness tests (SURVEY.md §7.7).

Runs tiny instances of each suite on the CPU test platform and checks cell
structure, verification gating, baseline lookups, and table rendering. The
full-size grid is exercised manually / by the driver on real hardware.
"""

import numpy as np
import pytest

from gauss_tpu.bench import baselines, grid


def test_reference_seconds_lookups():
    # Known cells from BASELINE.md tables.
    assert baselines.reference_seconds("gauss-internal", 2048, "omp") == 0.509428
    assert baselines.reference_seconds("gauss-internal", 2048, "tpu") == 0.509428
    assert baselines.reference_seconds("gauss-internal", 512, "seq") == 0.374293
    assert baselines.reference_seconds("gauss-internal", 512, "threads") is None
    assert baselines.reference_seconds("gauss-external", "sherman3", "tpu") == 11.584218
    assert baselines.reference_seconds("gauss-external", "jpwh_991", "forkjoin") == 0.233257
    assert baselines.reference_seconds("matmul", 2048, "tpu-pallas") == 0.114906
    # Device matmul engines compete with the reference's CUDA best, not the
    # CPU OpenMP row (the gauss-side mapping must not leak into matmul).
    assert baselines.reference_seconds("matmul", 1024, "tpu") == 0.089706
    assert baselines.reference_seconds("matmul", 2048, "tpu-pallas-v1") == 0.22632
    assert baselines.reference_seconds("matmul", 1024, "seq") == 1.39945
    assert baselines.reference_seconds("matmul", 999, "tpu") is None
    with pytest.raises(ValueError):
        baselines.reference_seconds("nope", 1, "tpu")


def test_suite_keys_match_reports():
    assert baselines.suite_keys("gauss-internal") == (128, 256, 512, 1024, 2048)
    assert baselines.suite_keys("matmul") == (1001, 1024, 2001, 2048)
    assert "sherman3" in baselines.suite_keys("gauss-external")


def test_gauss_internal_grid_cells():
    cells = grid.run_suite("gauss-internal", [32, 64], ["tpu-unblocked"])
    assert len(cells) == 2
    for c in cells:
        assert c.verified, f"residual {c.error}"
        assert c.seconds > 0
        assert c.speedup is None or c.speedup > 0


def test_gauss_external_grid_cell():
    cells = grid.run_suite("gauss-external", ["matrix_10"], ["tpu-unblocked"])
    (c,) = cells
    assert c.verified, f"max rel error {c.error}"
    assert c.key == "matrix_10"
    assert c.reference_s is None  # no report row for matrix_10


def test_matmul_grid_cell():
    cells = grid.run_suite("matmul", [64], ["tpu"])
    (c,) = cells
    assert c.verified
    assert c.seconds > 0


def test_format_table_marks_failures_and_baselines():
    cells = [
        grid.Cell("gauss-internal", "2048", "tpu", 0.0509428, True, 1e-9, 0.509428),
        grid.Cell("gauss-internal", "2048", "seq", 1.0, False, 0.5, 10.977564),
    ]
    table = grid.format_table(cells)
    assert "(10.0xR)" in table      # speedup column
    assert "FAILED" in table        # unverified cell never shows as a time
    assert "| n |" in table


def test_grid_cli_main(tmp_path, capsys):
    out = tmp_path / "cells.json"
    rc = grid.main(["--suite", "gauss-internal", "--keys", "16,32",
                    "--backends", "tpu-unblocked", "--json", str(out)])
    assert rc == 0
    import json

    cells = json.loads(out.read_text())
    assert len(cells) == 2 and all(c["verified"] for c in cells)
    assert "gauss-internal" in capsys.readouterr().out


def test_run_suite_survives_a_broken_backend(monkeypatch, capsys):
    from gauss_tpu.cli import _common

    real = _common.solve_with_backend

    def flaky(a, b, backend, **kw):
        if backend == "seq":
            raise RuntimeError("native library unavailable")
        return real(a, b, backend, **kw)

    monkeypatch.setattr(_common, "solve_with_backend", flaky)
    cells = grid.run_suite("gauss-internal", [16], ["tpu-unblocked", "seq"])
    assert len(cells) == 2
    ok, broken = cells
    assert ok.verified and not broken.verified
    assert "seq failed" in capsys.readouterr().err
    assert "FAILED" in grid.format_table(cells)


def test_run_suite_survives_a_bad_key(capsys):
    cells = grid.run_suite("gauss-external", ["shermn3", "matrix_10"],
                           ["tpu-unblocked"])
    assert len(cells) == 2
    bad, good = cells
    assert not bad.verified and np.isnan(bad.error)
    assert good.verified
    assert "setup failed" in capsys.readouterr().err


def test_grid_cli_json_is_strict_when_cells_fail(tmp_path, monkeypatch):
    from gauss_tpu.cli import _common

    def broken(*a, **k):
        raise RuntimeError("boom")

    monkeypatch.setattr(_common, "solve_with_backend", broken)
    out = tmp_path / "cells.json"
    rc = grid.main(["--suite", "gauss-internal", "--keys", "16",
                    "--backends", "tpu-unblocked", "--json", str(out)])
    assert rc == 1
    import json

    (cell,) = json.loads(out.read_text())  # strict parse must succeed
    assert cell["error"] is None and not cell["verified"]


def test_grid_cli_rejects_unknown_backend(capsys):
    with pytest.raises(SystemExit):
        grid.main(["--suite", "matmul", "--backends", "tpu,thread"])
    assert "unknown backend" in capsys.readouterr().err


def test_grid_cli_rejects_non_integer_sizes(capsys):
    with pytest.raises(SystemExit):
        grid.main(["--suite", "matmul", "--keys", "2048,sherman5",
                   "--backends", "tpu"])
    assert "integer sizes" in capsys.readouterr().err


def test_external_class_tracks_backend_class():
    # Derivation guard: every backend with a reference class resolves for
    # the external suite too (pthreads-v* collapse to the report's single
    # Pthreads column).
    for backend, cls in baselines.BACKEND_CLASS.items():
        got = baselines._EXTERNAL_CLASS[backend]
        assert got == ("pthreads" if cls.startswith("pthreads") else cls)


def test_grid_cli_rejects_keys_with_all_suites(capsys):
    with pytest.raises(SystemExit):
        grid.main(["--keys", "512", "--backends", "tpu-unblocked"])
    assert "--keys requires a single --suite" in capsys.readouterr().err


def test_grid_cli_nothing_ran_is_failure(capsys):
    # "threads" is a gauss engine with no matmul counterpart.
    rc = grid.main(["--suite", "matmul", "--backends", "threads"])
    assert rc == 1
    assert "nothing ran" in capsys.readouterr().err


def test_grid_device_span_gauss_and_matmul():
    """--span device: slope-timed cells for device engines, tagged 'device';
    ineligible backends keep the reference span."""
    cells = grid.run_suite("gauss-internal", [32], ["tpu", "seq"],
                           span="device")
    by_backend = {c.backend: c for c in cells}
    assert by_backend["tpu"].span == "device"
    assert by_backend["tpu"].verified and by_backend["tpu"].seconds > 0
    assert by_backend["seq"].span == "reference"

    mm = grid.run_suite("matmul", [32], ["tpu"], span="device")
    assert mm[0].span == "device" and mm[0].verified and mm[0].seconds > 0


def test_grid_jax_linalg_baseline_column():
    """The stock-library baseline column (VERDICT r3 next #4):
    jax.scipy.linalg.solve runs as a slope-timed device-span cell; in the
    reference span it fails loudly instead of silently timing nothing."""
    cells = grid.run_suite("gauss-internal", [32], ["jax-linalg"],
                           span="device")
    assert cells[0].span == "device"
    assert cells[0].verified and cells[0].seconds > 0
    ref_cells = grid.run_suite("gauss-internal", [32], ["jax-linalg"])
    assert not ref_cells[0].verified
    assert "device-span-only" in ref_cells[0].note


def test_grid_cli_accepts_jax_linalg(tmp_path):
    """The bench-only baseline backend must pass the CLI's backend
    validation (it is not in _common.GAUSS_BACKENDS — round-4 regression:
    the device-span regen stages all died on p.error)."""
    out = tmp_path / "c.json"
    rc = grid.main(["--suite", "gauss-internal", "--keys", "32",
                    "--backends", "jax-linalg", "--span", "device",
                    "--json", str(out)])
    assert rc == 0
    import json

    cells = json.loads(out.read_text())
    assert cells[0]["backend"] == "jax-linalg" and cells[0]["verified"]


def test_grid_matmul_sampled_verification(monkeypatch):
    """n >= MATMUL_SAMPLE_N: exact f64 truth on a seeded row sample, device
    span only, the sample labeled in the note; the reference span refuses
    loudly instead of silently timing a multi-GB fetch."""
    monkeypatch.setattr(grid, "MATMUL_SAMPLE_N", 64)
    monkeypatch.setattr(grid, "MATMUL_SAMPLE_ROWS", 8)
    cells = grid.run_suite("matmul", [96], ["tpu"], span="device")
    assert cells[0].span == "device"
    assert cells[0].verified and cells[0].seconds > 0
    assert "8-row sample" in cells[0].note
    ref = grid.run_suite("matmul", [96], ["tpu"])
    assert not ref[0].verified and "device span" in ref[0].note


def test_grid_rejects_unknown_span():
    with pytest.raises(ValueError, match="span"):
        grid.run_suite("matmul", [16], ["tpu"], span="bogus")


def test_grid_thread_sweep_keys_and_device_dedup():
    cells = grid.run_suite("gauss-internal", [32], ["seq", "tpu-unblocked"],
                           thread_sweep=[1, 2])
    labels = [(c.key, c.backend) for c in cells]
    assert ("32 @1t", "seq") in labels and ("32 @2t", "seq") in labels
    # device engines have no thread axis: swept once, keyed by the bare size
    assert ("32", "tpu-unblocked") in labels
    assert not any("@" in k and b == "tpu-unblocked" for k, b in labels)
    assert all(c.verified for c in cells)


def test_grid_thread_sweep_prep_failure_keys_consistent():
    cells = grid.run_suite("gauss-external", ["bogus_matrix"], ["seq", "tpu"],
                           thread_sweep=[1, 2])
    labels = [(c.key, c.backend) for c in cells]
    assert ("bogus_matrix @1t", "seq") in labels
    assert ("bogus_matrix @2t", "seq") in labels
    assert ("bogus_matrix", "tpu") in labels
    assert len(labels) == 3 and not any(c.verified for c in cells)


def test_grid_device_span_rowelim():
    """BASELINE config 2's engine (Pallas per-step row elimination) gets
    slope-timed device cells, verified on the exact timed configuration."""
    cells = grid.run_suite("gauss-internal", [32], ["tpu-rowelim"],
                           span="device")
    assert cells[0].span == "device"
    assert cells[0].verified and cells[0].seconds > 0


def test_grid_device_span_ineligible_engine_notice(capsys):
    """--span device on an engine with no device-span implementation keeps
    the reference span and says so on stderr (never silently mixes spans)."""
    cells = grid.run_suite("gauss-external", ["matrix_10"], ["tpu-rowelim"],
                           span="device")
    assert cells[0].span == "reference"
    assert "no device span for this suite" in capsys.readouterr().err


def test_gauss_dist_suite():
    """The distributed shard-sweep suite (VERDICT r1 #7): every cell runs on
    the virtual CPU mesh, verifies the residual bar, keys on shards, and
    carries the not-ICI provenance note."""
    from gauss_tpu.bench import grid

    cells = grid.run_suite("gauss-dist", [64],
                           ["tpu-dist", "tpu-dist-blocked"],
                           thread_sweep=[2, 4])
    assert len(cells) == 4
    assert {c.key for c in cells} == {"64 @2sh", "64 @4sh"}
    for c in cells:
        assert c.verified, (c.backend, c.key, c.error)
        assert c.seconds > 0
        assert c.note == grid.DIST_NOTE
        assert c.span == "reference"
    table = grid.format_table(cells)
    assert "@2sh" in table and grid.DIST_NOTE in table


def test_gauss_dist_suite_rejects_non_dist_backend():
    from gauss_tpu.bench import grid

    cells = grid.run_suite("gauss-dist", [32], ["seq"], thread_sweep=[2])
    assert len(cells) == 1 and not cells[0].verified


def test_gauss_dist_default_device_mesh(monkeypatch):
    """--dist-device default builds the mesh from jax.devices() of the
    default platform instead of the forced CPU pool (the real-TPU
    1-chip-mesh proof of VERDICT r4 next #7; on the CPU test mesh the
    default platform IS cpu, so this exercises the routing and the
    provenance note, and the committed reports/cells_gauss_dist_tpu1.json
    carries the real-chip run). Shard counts past the device pool raise
    the sizing error, not an obscure mesh failure."""
    from gauss_tpu.bench import grid

    monkeypatch.setattr(grid, "DIST_DEVICE", "default")
    cells = grid.run_suite("gauss-dist", [64], ["tpu-dist"], thread_sweep=[1])
    assert len(cells) == 1 and cells[0].verified
    assert cells[0].note.startswith("real cpu mesh=1")

    import jax

    too_many = len(jax.devices()) + 1
    bad = grid.run_suite("gauss-dist", [64], ["tpu-dist"],
                         thread_sweep=[too_many])
    assert len(bad) == 1 and not bad[0].verified
    assert "devices" in (bad[0].note or "")


def test_infra_retryable_classifier():
    # gRPC/daemon/transport shapes retry; deterministic bugs never do.
    assert grid._infra_retryable(RuntimeError(
        "UNAVAILABLE: connection to TPU daemon lost"))
    assert grid._infra_retryable(RuntimeError(
        "DEADLINE_EXCEEDED waiting for worker"))
    assert grid._infra_retryable(OSError("Connection reset by peer"))
    assert grid._infra_retryable(RuntimeError(
        "compile failed 2026-08-04T10:11:12.345Z daemon restarting"))
    assert not grid._infra_retryable(ValueError("bad shape (3, 4)"))
    assert not grid._infra_retryable(TypeError("not an array"))
    assert not grid._infra_retryable(AssertionError("residual too large"))
    assert not grid._infra_retryable(RuntimeError("some deterministic bug"))


def test_run_suite_retries_infra_failure_once(monkeypatch, capsys):
    """An infra-class failure gets ONE retry; the retried cell verifies and
    its note records BOTH timestamps (first failure + retry) so the cell is
    visibly a second attempt, never a clean first run."""
    from gauss_tpu.cli import _common

    real = _common.solve_with_backend
    calls = {"n": 0}

    def flaky_once(a, b, backend, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("UNAVAILABLE: tunnel dropped")
        return real(a, b, backend, **kw)

    monkeypatch.setattr(_common, "solve_with_backend", flaky_once)
    cells = grid.run_suite("gauss-internal", [16], ["tpu-unblocked"])
    assert len(cells) == 1 and cells[0].verified
    assert "retried: infra-class failure at " in cells[0].note
    assert "-> succeeded at " in cells[0].note
    assert "UNAVAILABLE" in cells[0].note
    assert "retrying once" in capsys.readouterr().err


def test_run_suite_reproduced_infra_failure_stays_failed(monkeypatch,
                                                         capsys):
    """A failure that reproduces on the retry stays FAILED honestly, with
    both attempts' timestamps and notes in the cell."""
    from gauss_tpu.cli import _common

    def always_down(a, b, backend, **kw):
        raise RuntimeError("UNAVAILABLE: tunnel down")

    monkeypatch.setattr(_common, "solve_with_backend", always_down)
    cells = grid.run_suite("gauss-internal", [16], ["tpu-unblocked"])
    assert len(cells) == 1 and not cells[0].verified
    note = cells[0].note
    assert "[at 20" in note and "retry reproduced at 20" in note
    assert note.count("UNAVAILABLE") == 2


def test_run_suite_deterministic_failure_not_retried(monkeypatch):
    from gauss_tpu.cli import _common

    calls = {"n": 0}

    def det_bug(a, b, backend, **kw):
        calls["n"] += 1
        raise ValueError("deterministic shape bug")

    monkeypatch.setattr(_common, "solve_with_backend", det_bug)
    cells = grid.run_suite("gauss-internal", [16], ["tpu-unblocked"])
    assert len(cells) == 1 and not cells[0].verified
    assert calls["n"] == 1          # no second attempt
    assert "retried" not in cells[0].note
