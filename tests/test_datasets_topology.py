"""Dataset registry and meshfile tests."""

import numpy as np
import pytest

from gauss_tpu.dist import topology, make_mesh
from gauss_tpu.io import datasets, datfile


def test_registry_shapes():
    assert datasets.REGISTRY["sherman3"] == (5005, 20033)
    assert datasets.REGISTRY["jpwh_991"] == (991, 6027)


@pytest.mark.parametrize("name", ["matrix_10", "jpwh_991"])
def test_dataset_deterministic(name):
    n1, r1, c1, v1 = datasets.dataset_coords(name)
    n2, r2, c2, v2 = datasets.dataset_coords(name)
    assert n1 == n2
    np.testing.assert_array_equal(r1, r2)
    np.testing.assert_array_equal(v1, v2)
    assert len(v1) == datasets.REGISTRY[name][1]


def test_dataset_solvable():
    """Stand-ins are diagonally dominant, so the external-input flow works."""
    from gauss_tpu.core.gauss import gauss_solve
    from gauss_tpu.io import synthetic
    from gauss_tpu.verify import checks

    a = datasets.dataset_dense("jpwh_991")[:200, :200]  # leading block, still dominant
    x_true = synthetic.manufactured_solution(200)
    b = synthetic.manufactured_rhs(a, x_true)
    x = np.asarray(gauss_solve(a, b))
    assert checks.max_rel_error(x, x_true) < 1e-8


def test_dataset_roundtrip(tmp_path):
    p = tmp_path / "jpwh_991.dat"
    datasets.write_dataset("jpwh_991", p)
    dense = datfile.read_dat_dense(p, engine="python")
    np.testing.assert_array_equal(dense, datasets.dataset_dense("jpwh_991"))


def test_dataset_unknown_name():
    with pytest.raises(KeyError):
        datasets.dataset_coords("bcsstk01")


def test_datasets_cli(tmp_path, capsys):
    from gauss_tpu.cli import datasets as cli

    rc = cli.main(["matrix_10", "--out", str(tmp_path)])
    assert rc == 0
    assert (tmp_path / "matrix_10.dat").exists()
    rc = cli.main(["--list"])
    assert rc == 0
    assert "memplus" in capsys.readouterr().out
    assert cli.main(["nope"]) == 1


def test_meshfile_parse_and_load(tmp_path):
    p = tmp_path / "meshfile"
    p.write_text("# six-node analog\naxis rows 4\naxis cols 2\n")
    mesh = topology.load_meshfile(p)
    assert mesh.devices.shape == (4, 2)
    assert mesh.axis_names == ("rows", "cols")


def test_meshfile_errors(tmp_path):
    with pytest.raises(ValueError, match="expected 'axis"):
        topology.parse_meshfile("rows 4")
    with pytest.raises(ValueError, match="duplicate"):
        topology.parse_meshfile("axis r 2\naxis r 2")
    with pytest.raises(ValueError, match="no axes"):
        topology.parse_meshfile("# nothing\n")
    p = tmp_path / "meshfile"
    p.write_text("axis rows 64\n")
    with pytest.raises(ValueError, match="64 devices"):
        topology.load_meshfile(p)


def test_meshfile_drives_dist_solve(tmp_path, rng):
    from gauss_tpu.dist import gauss_dist

    p = tmp_path / "meshfile"
    p.write_text("axis rows 4\n")
    mesh = topology.load_meshfile(p)
    n = 32
    a = rng.standard_normal((n, n))
    b = rng.standard_normal(n)
    x = np.asarray(gauss_dist.gauss_solve_dist(a, b, mesh=mesh))
    np.testing.assert_allclose(x, np.linalg.solve(a, b), rtol=1e-9, atol=1e-9)


def test_dataset_golden_checksums():
    """The stand-in matrices are part of the framework's contract: coordinate
    streams must be bitwise reproducible across runs, machines, and numpy
    versions (golden CRCs pinned from the first release). A mismatch means
    benchmark results stop being comparable across rounds."""
    import zlib

    import numpy as np

    golden = {
        "matrix_10": 0x478aae81,
        "jpwh_991": 0xa671c8b9,
        "orsreg_1": 0x6da9a493,
        "sherman5": 0xb82e3b38,
        "saylr4": 0x3023f777,
        "sherman3": 0x209f7c59,
        "memplus": 0x5dc57880,
        "matrix_2000": 0x816c8578,
    }
    for name, want in golden.items():
        n, r, c, v = datasets.dataset_coords(name)
        crc = zlib.crc32(np.ascontiguousarray(r).tobytes())
        crc = zlib.crc32(np.ascontiguousarray(c).tobytes(), crc)
        crc = zlib.crc32(
            np.ascontiguousarray(np.asarray(v, np.float64)).tobytes(), crc)
        assert crc == want, f"{name}: dataset stream drifted (0x{crc:08x})"
