"""gauss_tpu.tune: store semantics, consult fallbacks, sweep, compile cache.

The store's failure contract is the heart of the suite: a corrupt, stale,
or foreign store must NEVER change solver behavior — every degradation is
a typed TuneStoreError internally and a seed-default fallback at the
consult sites, with the reason visible as data.
"""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from gauss_tpu import obs
from gauss_tpu.tune import apply, space, store
from gauss_tpu.tune.store import TuneStore, TuneStoreError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def store_env(tmp_path, monkeypatch):
    """Point the consult path at a per-test store location and isolate its
    process-lifetime caches (including the jit caches, which bake tuned
    trace-time resolutions into compiled programs)."""
    path = tmp_path / "tune_store.json"
    monkeypatch.setenv(store.ENV_STORE, str(path))
    apply.reset_cache()
    yield path
    apply.reset_cache()
    jax.clear_caches()


def _current_store(configs=None) -> TuneStore:
    jax.devices()  # make the backend fingerprint knowable
    return TuneStore(fingerprint=store.store_fingerprint(),
                     configs=configs or {})


# -- store file semantics ----------------------------------------------------

def test_store_roundtrip_determinism(tmp_path):
    st = _current_store()
    st.put("lu_factor", 2048, {"panel": 256, "chunk": 8},
           seconds=0.0015, seed_seconds=0.0017, source="testrun")
    p1, p2 = tmp_path / "a.json", tmp_path / "b.json"
    st.save(p1)
    loaded = TuneStore.load(p1)
    assert loaded.to_doc() == st.to_doc()
    loaded.save(p2)
    assert p1.read_bytes() == p2.read_bytes()
    assert loaded.params("lu_factor", 2000) == {
        "panel": 256, "chunk": 8, "refine_steps": 2}
    # a different n-bucket sees pure seeds
    assert loaded.params("lu_factor", 4096) == space.seed_params("lu_factor")


@pytest.mark.parametrize("payload", [
    "{ not json at all",                      # corrupt
    '{"version": 1, "configs": {"k": ',       # truncated mid-write
    '{"version": 99, "configs": {}, "fingerprint": {}}',   # future schema
    '{"version": 1, "fingerprint": {}}',      # missing configs
    '{"version": 1, "configs": {"k": {"no_params": 1}}, '
    '"fingerprint": {}}',                     # entry without params
    "[1, 2, 3]",                              # wrong top-level type
])
def test_bad_store_raises_typed_and_falls_back(store_env, payload):
    store_env.write_text(payload)
    with pytest.raises(TuneStoreError):
        TuneStore.load(store_env)
    # The consult path degrades to seeds instead of raising...
    assert apply.params_for("lu_factor", 2048) == \
        space.seed_params("lu_factor")
    assert apply.override("lu_factor", 2048, "panel") is None
    # ...and names the reason.
    status = apply.store_status()
    assert not status["usable"]
    assert status["reason"].startswith("store_error")


def test_fingerprint_mismatch_falls_back(store_env):
    jax.devices()
    foreign = TuneStore(fingerprint={"backend": "tpu",
                                     "device_kind": "TPU v99",
                                     "device_count": 4096})
    foreign.put("lu_factor", 2048, {"panel": 64})
    foreign.save(store_env)
    assert apply.override("lu_factor", 2048, "panel") is None
    assert apply.store_status()["reason"] == "fingerprint_mismatch"
    # The same entry under THIS environment's fingerprint is honored.
    mine = _current_store(foreign.configs)
    mine.save(store_env)
    apply.reset_cache()
    assert apply.override("lu_factor", 2048, "panel") == 64


def test_absent_store_is_zero_change(store_env):
    from gauss_tpu.core import blocked

    assert not store_env.exists()
    assert apply.store_status() == {"path": str(store_env),
                                    "usable": False, "reason": "absent",
                                    "configs": 0}
    # the auto heuristics resolve exactly as before the tune subsystem
    assert blocked.auto_panel(512) == blocked.DEFAULT_PANEL
    assert blocked.auto_panel(2048) in (128, 256)
    assert apply.params_for("lu_factor", 2048) == \
        space.seed_params("lu_factor")


def test_suspended_hides_a_good_store(store_env):
    st = _current_store()
    st.put("lu_factor", 1024, {"panel": 64})
    st.save(store_env)
    apply.reset_cache()
    assert apply.override("lu_factor", 1024, "panel") == 64
    with apply.suspended():
        assert apply.override("lu_factor", 1024, "panel") is None
        assert apply.params_for("lu_factor", 1024) == \
            space.seed_params("lu_factor")
    assert apply.override("lu_factor", 1024, "panel") == 64


# -- consult integration -----------------------------------------------------

def test_auto_panel_consults_store_and_announces(store_env):
    from gauss_tpu.core import blocked

    st = _current_store()
    st.put("lu_factor", 2048, {"panel": 64, "chunk": 2})
    st.save(store_env)
    apply.reset_cache()
    with obs.run(metrics_out=None, tool="tune_test") as rec:
        assert blocked.auto_panel(2048) == 64
        # same bucket, different n
        assert blocked.auto_panel(1500) == 64
        # untuned bucket keeps the heuristic
        assert blocked.auto_panel(512) == blocked.DEFAULT_PANEL
        evs = [e for e in rec.events if e.get("type") == "tune"]
    assert evs and evs[0]["source"] == "store"
    assert evs[0]["key"] == "lu_factor/n2048/float32/blocked"
    assert rec.counters.get("tune.store_hits", 0) >= 1


def test_vmem_budget_override_and_monkeypatch_priority(store_env,
                                                       monkeypatch):
    from gauss_tpu.core import blocked

    # Without a store the module global governs — including monkeypatched
    # values (the pre-existing kernel tests rely on this).
    with monkeypatch.context() as m:
        m.setattr(blocked, "PANEL_VMEM_BUDGET", 1024)
        assert not blocked.panel_fits_vmem(4096, 128)
    st = _current_store()
    st.put("panel_kernel", 4096, {"vmem_budget": 10})
    st.save(store_env)
    apply.reset_cache()
    assert not blocked.panel_fits_vmem(4096, 128)  # tuned budget: tiny
    assert blocked.panel_fits_vmem(512, 128)       # other bucket: seed


def test_serve_warmup_picks_up_tuned_panel(store_env):
    from gauss_tpu.serve.cache import CacheKey, ExecutableCache

    st = _current_store()
    st.put("lu_factor", 32, {"panel": 16})
    st.save(store_env)
    apply.reset_cache()
    key = CacheKey(bucket_n=32, nrhs=1, batch=1, dtype="float32",
                   engine="blocked", refine_steps=0)
    with obs.run(metrics_out=None, tool="tune_test") as rec:
        cache = ExecutableCache(capacity=2)
        exe = cache.get(key)
        consults = [e for e in rec.events if e.get("type") == "tune"
                    and e.get("source") == "store"]
    assert exe.panel == 16
    # tuning changes how the executable is BUILT, never which entry it is
    assert exe.key == key
    assert cache.keys() == [key]
    assert consults
    # the tuned executable still solves correctly at the bucket shape
    rng = np.random.default_rng(7)
    a = rng.standard_normal((1, 32, 32)) + 32 * np.eye(32)
    b = rng.standard_normal((1, 32, 1))
    x = exe.solve(a, b)
    assert np.linalg.norm(a[0] @ x[0] - b[0]) < 1e-3


def test_tuned_factor_bit_identical_to_explicit(store_env):
    import jax.numpy as jnp

    from gauss_tpu.core import blocked

    st = _current_store()
    st.put("lu_factor", 80, {"panel": 16})
    st.save(store_env)
    apply.reset_cache()
    jax.clear_caches()
    rng = np.random.default_rng(11)
    a = jnp.asarray(rng.standard_normal((80, 80)) + 80 * np.eye(80),
                    jnp.float32)
    fac_auto = blocked.lu_factor_blocked(a, panel=None)
    fac_explicit = blocked.lu_factor_blocked(a, panel=16)
    assert np.array_equal(np.asarray(fac_auto.m),
                          np.asarray(fac_explicit.m))
    assert np.array_equal(np.asarray(fac_auto.perm),
                          np.asarray(fac_explicit.perm))


# -- the sweep runner --------------------------------------------------------

def test_runner_micro_sweep_writes_concrete_store(store_env):
    from gauss_tpu.tune import runner

    summary = runner.run_sweep(["lu_factor"], [48], seed=1234, reps=1,
                               axes={"panel": [16, 32], "chunk": [1]},
                               run_id="sweeptest")
    assert summary["kind"] == "tune_sweep"
    (point,) = summary["points"]
    assert point["key"] == "lu_factor/n64/float32/blocked"
    assert point["best_s"] > 0 and point["seed_s"] > 0
    # winners are concretized: the auto seed config never pins "None"
    assert point["best_params"]["panel"] is not None
    runner.write_store(summary, store_env)
    loaded = TuneStore.load(store_env)
    entry = loaded.get("lu_factor", 48)
    assert entry["source"] == "sweeptest"
    assert entry["params"]["panel"] == point["best_params"]["panel"]
    recs = runner.history_records(summary)
    metrics = {m for m, _, _ in recs}
    assert "tune:lu_factor/n64/float32:s_per_solve" in metrics
    assert "tune:lu_factor/n64/float32:win_ratio" in metrics


def test_sweep_is_independent_of_existing_store(store_env):
    from gauss_tpu.tune import runner

    st = _current_store()
    st.put("lu_factor", 48, {"panel": 16})  # a pre-existing "winner"
    st.save(store_env)
    apply.reset_cache()
    summary = runner.run_sweep(["lu_factor"], [48], seed=1234, reps=1,
                               axes={"panel": [32], "chunk": [1]})
    # the seed baseline measured the SEED policy, not the stored panel=16
    assert summary["points"][0]["seed_params"]["panel"] is None


def test_regress_ingests_tune_sweep_summary(tmp_path):
    from gauss_tpu.obs import regress

    doc = {"kind": "tune_sweep",
           "points": [{"op": "lu_factor", "n": 96, "n_bucket": 128,
                       "dtype": "float32", "engine": "blocked",
                       "seed_s": 0.002, "best_s": 0.001,
                       "best_params": {"panel": 64}}]}
    path = tmp_path / "tune_summary.json"
    path.write_text(json.dumps(doc))
    recs = regress.ingest_file(path)
    by_metric = {r["metric"]: r for r in recs}
    assert by_metric["tune:lu_factor/n128/float32:s_per_solve"][
        "value"] == 0.001
    assert by_metric["tune:lu_factor/n128/float32:win_ratio"]["value"] == 0.5
    assert all(r["kind"] == "tune" for r in recs)


# -- observability -----------------------------------------------------------

def test_summarize_tuning_section(store_env, tmp_path):
    from gauss_tpu.core import blocked
    from gauss_tpu.obs import summarize

    st = _current_store()
    st.put("lu_factor", 256, {"panel": 64})
    st.save(store_env)
    apply.reset_cache()
    stream = tmp_path / "run.jsonl"
    with obs.run(metrics_out=str(stream), tool="tune_test") as rec:
        blocked.auto_panel(256)
        run_id = rec.run_id
    events = obs.read_events(stream)
    tn = summarize.run_summary(events, run_id)["tuning"]
    assert tn["store"]["hits"] == 1
    assert tn["consults"][0]["key"] == "lu_factor/n256/float32/blocked"
    assert tn["consults"][0]["source"] == "store"
    text = summarize.summarize_run(events, run_id)
    assert "tuning:" in text
    assert "lu_factor/n256/float32/blocked" in text


def test_xla_cache_listener_counts_into_obs():
    from gauss_tpu.obs import compile as obs_compile

    assert obs_compile.track_xla_cache()
    with obs.run(metrics_out=None, tool="tune_test") as rec:
        obs_compile._xla_cache_listener("/jax/compilation_cache/cache_hits")
        obs_compile._xla_cache_listener(
            "/jax/compilation_cache/cache_misses")
        obs_compile._xla_cache_listener("/jax/unrelated/event")
    assert rec.counters["xla.cache_hits"] == 1
    assert rec.counters["xla.cache_misses"] == 1


def test_compilecache_enable_and_env_channel(tmp_path, monkeypatch):
    from gauss_tpu.tune import compilecache

    cache_dir = tmp_path / "xla_cache"
    monkeypatch.delenv(compilecache.ENV_CACHE_DIR, raising=False)
    try:
        got = compilecache.enable(str(cache_dir))
        assert got == str(cache_dir)
        assert compilecache.enabled()
        assert compilecache.cache_dir() == str(cache_dir)
        # the env channel is exported for subprocesses (fleet workers)
        assert os.environ[compilecache.ENV_CACHE_DIR] == str(cache_dir)
        assert jax.config.jax_compilation_cache_dir == str(cache_dir)
    finally:
        compilecache._enabled_dir = None
        jax.config.update("jax_compilation_cache_dir", None)
        os.environ.pop(compilecache.ENV_CACHE_DIR, None)


def test_fleet_config_carries_compile_cache_dir():
    from gauss_tpu.resilience.fleet import FleetConfig

    cfg = FleetConfig(compile_cache_dir="/tmp/somewhere")
    assert cfg.compile_cache_dir == "/tmp/somewhere"


# -- the CI gate end to end (subprocess-heavy: slow) -------------------------

@pytest.mark.slow
def test_tune_check_gate_end_to_end(tmp_path):
    r = subprocess.run(
        [sys.executable, "-m", "gauss_tpu.tune.check", "--n", "64",
         "--reps", "1", "--tmpdir", str(tmp_path / "work"),
         "--summary-json", str(tmp_path / "summary.json")],
        cwd=REPO, capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "warm start ok" in r.stdout
    summary = json.loads((tmp_path / "summary.json").read_text())
    ws = summary["warm_start"]
    assert ws["warm_compiles"] < ws["cold_compiles"]
