"""Live telemetry plane tests: rolling-window/quantile math vs numpy, the
Prometheus exposition format (golden), the embedded HTTP endpoints,
end-to-end trace_id propagation (batched / retry->recovery / handoff —
exactly one trace per request), SLO burn-rate alert hysteresis, SLO-
degraded shedding, on-demand /trace capture from a running server,
gauss-top --once, the doctor span diff, and the slo_report regress ingest.

All CPU (conftest pins the platform); the module-scoped live server keeps
the jitted-executable compiles to one small set shared across tests.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from gauss_tpu import obs
from gauss_tpu.obs import doctor, regress, requesttrace, summarize
from gauss_tpu.obs import export as obs_export
from gauss_tpu.obs import live as obs_live
from gauss_tpu.obs import top as obs_top
from gauss_tpu.obs.slo import SLO, SLOMonitor, slo_report
from gauss_tpu.serve import ServeConfig, SolverServer

LADDER = (16, 32)


def _system(rng, n):
    a = rng.standard_normal((n, n))
    a[np.arange(n), np.arange(n)] += float(n)
    return a, rng.standard_normal(n)


def _config(**over):
    kw = dict(ladder=LADDER, max_batch=4, panel=16, refine_steps=1,
              verify_gate=1e-4, live_port=0)
    kw.update(over)
    return ServeConfig(**kw)


@pytest.fixture(scope="module")
def live_server():
    with SolverServer(_config()) as srv:
        yield srv


# -- rolling windows / percentile sketch -----------------------------------

def test_quantile_matches_numpy(rng):
    vals = sorted(rng.standard_normal(257).tolist())
    for q in (0.0, 0.25, 0.5, 0.95, 0.99, 1.0):
        np.testing.assert_allclose(obs_live.quantile(vals, q),
                                   np.quantile(vals, q), rtol=1e-12)
    assert obs_live.quantile([], 0.5) is None
    assert obs_live.quantile([7.0], 0.99) == 7.0


def test_rolling_window_ring_and_quantiles(rng):
    win = obs_live.RollingWindow(capacity=128, horizon_s=None)
    vals = rng.standard_normal(500).tolist()
    for v in vals:
        win.add(v)
    # the ring keeps exactly the LAST 128 observations
    survivors = vals[-128:]
    assert sorted(win.values()) == sorted(survivors)
    assert win.count == 500
    np.testing.assert_allclose(win.total, sum(vals))
    got = win.quantiles((0.5, 0.95, 0.99))
    for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
        np.testing.assert_allclose(got[key], np.quantile(survivors, q),
                                   rtol=1e-12)


def test_rolling_window_horizon_eviction():
    win = obs_live.RollingWindow(capacity=64, horizon_s=10.0)
    for i in range(5):
        win.add(float(i), t=100.0 + i)   # t = 100..104
    # at now=112, samples older than 102 have aged out
    assert sorted(win.values(now=112.0)) == [2.0, 3.0, 4.0]
    assert win.values(now=200.0) == []
    with pytest.raises(ValueError):
        obs_live.RollingWindow(capacity=0)


def test_rolling_window_horizon_under_sparse_writes():
    # Sparse traffic: the ring never fills, so stale samples are not
    # overwritten — they must still age out at READ time, per-call, and a
    # fresh burst must not resurrect them.
    win = obs_live.RollingWindow(capacity=256, horizon_s=60.0)
    win.add(1.0, t=0.0)
    win.add(2.0, t=10.0)          # a quiet first minute
    assert sorted(win.values(now=30.0)) == [1.0, 2.0]
    win.add(3.0, t=500.0)         # then nothing for ~8 minutes
    assert win.values(now=505.0) == [3.0]     # old pair aged out unwritten
    assert win.values(now=600.0) == []        # everything stale
    # all-time accounting is horizon-independent
    assert win.count == 3 and win.total == 6.0
    # a later burst only exposes in-horizon samples; the ring still holds
    # the stale ones physically (len(_buf) == 7) but readers never see them
    for i in range(4):
        win.add(10.0 + i, t=1000.0 + i)
    assert sorted(win.values(now=1003.0)) == [10.0, 11.0, 12.0, 13.0]
    assert len(win._buf) == 7
    # per-call horizon override widens the view without mutating state
    assert len(win.values(now=1003.0, horizon_s=1500.0)) == 7
    assert sorted(win.values(now=1003.0)) == [10.0, 11.0, 12.0, 13.0]


def test_rolling_window_sparse_writes_property(rng):
    # Property test for the sparse-write horizon semantics: random
    # interleavings of writes and clock advances, checked against a
    # brute-force (timestamp, value) list after EVERY operation. Catches
    # ring-index bugs the directed sparse-writes test above only samples
    # (stale slots resurrected after wrap, horizon applied at write
    # instead of read, count/total drifting from the all-time ledger).
    for case in range(20):
        case_rng = np.random.default_rng(900 + case)
        cap = int(case_rng.integers(2, 17))
        horizon = float(case_rng.uniform(5.0, 50.0))
        win = obs_live.RollingWindow(capacity=cap, horizon_s=horizon)
        ref = []          # brute-force: every (t, v) ever written
        now = 0.0
        for _ in range(120):
            if case_rng.random() < 0.6:
                v = float(case_rng.standard_normal())
                win.add(v, t=now)
                ref.append((now, v))
            else:
                # advances are mostly small, occasionally a long quiet
                # stretch that ages out the whole window unwritten
                now += float(case_rng.uniform(0.1, 4.0)
                             if case_rng.random() < 0.8
                             else case_rng.uniform(horizon, 3 * horizon))
            survivors = [v for t, v in ref[-cap:] if t >= now - horizon]
            assert sorted(win.values(now=now)) == sorted(survivors), \
                f"case={case} now={now}"
        assert win.count == len(ref)
        np.testing.assert_allclose(win.total, sum(v for _, v in ref))


def test_rolling_window_quantile_exact_at_capacity_boundary(rng):
    cap = 64
    for total in (cap - 1, cap, cap + 1, 3 * cap + 5):
        win = obs_live.RollingWindow(capacity=cap, horizon_s=None)
        vals = rng.standard_normal(total).tolist()
        for v in vals:
            win.add(v)
        survivors = vals[-cap:]
        assert len(win.values()) == min(total, cap)
        got = win.quantiles((0.0, 0.5, 0.95, 0.99, 1.0))
        for q, key in ((0.0, "p0"), (0.5, "p50"), (0.95, "p95"),
                       (0.99, "p99"), (1.0, "p100")):
            np.testing.assert_allclose(
                got[key], np.quantile(survivors, q), rtol=1e-12,
                err_msg=f"total={total} q={q}")


def test_aggregator_counters_gauges_windows_and_rates():
    agg = obs_live.LiveAggregator()
    agg.on_counter("serve.served", 3)
    agg.on_counter("serve.served", 2)
    agg.on_gauge("serve.queue_depth", 7)
    for v in (0.1, 0.2, 0.3, 0.4):
        agg.on_histogram("serve.latency_s", v)
    agg.on_span("factor", 0.5, None, 0, {})
    snap = agg.snapshot()
    assert snap["counters"]["serve.served"] == 5
    assert snap["gauges"]["serve.queue_depth"] == 7
    lat = snap["windows"]["serve.latency_s"]
    assert lat["count"] == 4
    np.testing.assert_allclose(lat["p50"], np.quantile([0.1, 0.2, 0.3, 0.4],
                                                       0.5))
    assert "span.factor.s" in snap["windows"]
    # windowed rate: 5 increments over the last minute
    assert agg.window_rate("serve.served", 60.0) == pytest.approx(5 / 60.0)
    assert agg.window_rate("nope", 60.0) == 0.0


def test_live_sink_receives_obs_hooks_without_recorder():
    agg = obs_live.LiveAggregator()
    prev = obs_live.install(agg)
    try:
        assert obs.active() is None  # no recorder — live sink alone
        obs.counter("x.hits")
        obs.gauge("x.depth", 2)
        with obs.span("x_phase"):
            pass
        obs.emit("health", min_pivot=0.25, label="t")
    finally:
        obs_live.uninstall(prev)
    snap = agg.snapshot()
    assert snap["counters"]["x.hits"] == 1
    assert snap["gauges"]["x.depth"] == 2
    assert "span.x_phase.s" in snap["windows"]
    # health events become live gauges
    assert snap["gauges"]["health.min_pivot"] == 0.25
    # uninstalled: hooks are no-ops again
    obs.counter("x.hits")
    assert agg.snapshot()["counters"]["x.hits"] == 1


# -- exposition format (golden) --------------------------------------------

def test_prometheus_exposition_golden():
    agg = obs_live.LiveAggregator(slos=(SLO(),))
    agg.on_counter("serve.served", 12)
    agg.on_gauge("serve.queue_depth", 3)
    agg.on_histogram("serve.latency_s", 0.25)
    agg.on_histogram("serve.latency_s", 0.75)
    # the attribution plane's utilization gauges (ISSUE 17): the exported
    # names are part of the committed scrape format gauss-top reads
    agg.on_gauge("util.lane0.device_s_per_s", 0.25)
    agg.on_gauge("util.lane0.stall_frac", 0.125)
    agg.on_gauge("util.lane0.flops_frac", 0.0625)
    agg.on_gauge("util.blocked.achieved_flops_per_s", 2000000)
    snap = agg.snapshot()
    snap["uptime_s"] = 1.5  # pin the only nondeterministic value
    text = obs_export.render_prometheus(snap)
    lines = text.splitlines()
    assert "# TYPE gauss_live_uptime_s gauge" in lines
    assert "gauss_live_uptime_s 1.5" in lines
    assert "# TYPE gauss_serve_served_total counter" in lines
    assert "gauss_serve_served_total 12" in lines
    assert "gauss_serve_queue_depth 3" in lines
    assert "# TYPE gauss_util_lane0_device_s_per_s gauge" in lines
    assert "gauss_util_lane0_device_s_per_s 0.25" in lines
    assert "gauss_util_lane0_stall_frac 0.125" in lines
    assert "gauss_util_lane0_flops_frac 0.0625" in lines
    assert "gauss_util_blocked_achieved_flops_per_s 2000000" in lines
    assert "# TYPE gauss_serve_latency_s summary" in lines
    assert 'gauss_serve_latency_s{quantile="0.5"} 0.5' in lines
    assert "gauss_serve_latency_s_count 2" in lines
    assert "gauss_serve_latency_s_sum 1" in lines
    assert 'gauss_slo_burn_rate{slo="serve_ok",window="short"} 0' in lines
    assert 'gauss_slo_firing{slo="serve_ok"} 0' in lines
    assert 'gauss_slo_objective{slo="serve_ok"} 0.99' in lines
    assert text.endswith("\n")
    # rendering is deterministic — the format is a stable scrape target
    assert text == obs_export.render_prometheus(snap)
    # and gauss-top's parser round-trips it
    samples = obs_top.parse_metrics(text)
    flat = {n: v for n, labels, v in samples if not labels}
    assert flat["gauss_serve_served_total"] == 12
    q = {labels["quantile"]: v for n, labels, v in samples
         if n == "gauss_serve_latency_s" and labels}
    assert q["0.5"] == 0.5


def test_gauss_top_utilization_panel_golden():
    # The attribution plane's gauges render as the utilization panel; the
    # panel is absent entirely when no gauss_util_* gauge is exported
    # (ServeConfig(attr=None) — byte-identical scrape to pre-attr builds).
    agg = obs_live.LiveAggregator()
    agg.on_gauge("util.lane0.device_s_per_s", 0.5)
    agg.on_gauge("util.lane0.stall_frac", 0.25)
    agg.on_gauge("util.lane0.achieved_flops_per_s", 1.5e6)
    agg.on_gauge("util.lane0.flops_frac", 0.125)
    agg.on_gauge("util.blocked.achieved_flops_per_s", 3e6)
    agg.on_gauge("util.blocked.flops_frac", 0.25)
    text = obs_export.render_prometheus(agg.snapshot())
    frame = obs_top.render(obs_top._View(obs_top.parse_metrics(text)),
                           "test://")
    assert "  utilization (attribution plane):" in frame
    lane = next(ln for ln in frame.splitlines() if "lane 0:" in ln)
    assert "1500000 flop/s achieved" in lane
    assert "(0.1250 of peak)" in lane and "stall 0.2500" in lane
    assert "device-s/s 0.5000" in lane
    eng = next(ln for ln in frame.splitlines() if "engine blocked:" in ln)
    assert "3000000 flop/s achieved (0.2500 of peak)" in eng
    # attr off: no gauss_util_* gauges -> no panel
    plain = obs_top.render(obs_top._View(obs_top.parse_metrics(
        obs_export.render_prometheus(obs_live.LiveAggregator().snapshot()))),
        "test://")
    assert "utilization" not in plain


def test_metric_name_mangling():
    assert obs_export.metric_name("serve.cache.hits") == \
        "gauss_serve_cache_hits"
    assert obs_export.metric_name("span.serve_batch_solve.s") == \
        "gauss_span_serve_batch_solve_s"
    assert obs_export.metric_name("9weird-name") == "gauss__9weird_name"


# -- SLO burn-rate alerts ---------------------------------------------------

def test_slo_validation():
    with pytest.raises(ValueError):
        SLO(objective=1.0)
    with pytest.raises(ValueError):
        SLO(short_window_s=300.0, long_window_s=60.0)
    with pytest.raises(ValueError):
        SLO(fire_burn=1.0, clear_burn=1.0)


def test_slo_burn_alert_fires_and_clears_with_hysteresis():
    mon = SLOMonitor(SLO(objective=0.9, short_window_s=10.0,
                         long_window_s=60.0, fire_burn=2.0, clear_burn=1.0,
                         min_count=4))
    t = 1000.0
    transitions = []
    # healthy traffic: no alert
    for i in range(20):
        tr = mon.observe("ok", now=t + i * 0.1)
        assert tr is None
    t += 2.0
    # a violation burst: fires EXACTLY once (no flapping while it stays bad)
    for i in range(10):
        tr = mon.observe("expired", now=t + i * 0.1)
        if tr:
            transitions.append(tr)
    assert [tr["state"] for tr in transitions] == ["firing"]
    assert mon.firing and mon.alerts == 1
    assert transitions[0]["burn_short"] >= 2.0
    # good traffic inside the window: bad fraction decays but hysteresis
    # holds the alert until burn_short <= clear_burn
    t += 1.0
    for i in range(60):
        tr = mon.observe("ok", now=t + i * 0.05)
        if tr:
            transitions.append(tr)
    t += 11.0  # bad observations age fully out of the short window
    tr = mon.observe("ok", now=t)
    if tr:
        transitions.append(tr)
    assert [tr["state"] for tr in transitions] == ["firing", "clear"]
    assert not mon.firing and mon.clears == 1
    assert mon.worst_burn >= 2.0


def test_slo_min_count_and_long_window_guard():
    # one early bad request must NOT page: min_count gates the short
    # window, and the long window needs sustained burn.
    mon = SLOMonitor(SLO(objective=0.99, short_window_s=10.0,
                         long_window_s=60.0, fire_burn=2.0, clear_burn=1.0,
                         min_count=4))
    assert mon.observe("failed", now=100.0) is None
    assert not mon.firing
    # cancelled is ignored entirely (neither good nor bad)
    mon.observe("cancelled", now=100.1)
    assert mon.good + mon.bad == 1


def test_slo_report_and_regress_ingest(tmp_path):
    mon = SLOMonitor(SLO(objective=0.9, short_window_s=10.0,
                         long_window_s=60.0, fire_burn=2.0, clear_burn=1.0,
                         min_count=2))
    t = 0.0
    for status in ("ok", "ok", "expired", "expired", "expired", "ok"):
        mon.observe(status, now=t)
        t += 0.5
    report = slo_report([mon], mix="test")
    assert report["kind"] == "slo_report"
    assert report["requests_counted"] == 6 and report["violations"] == 3
    assert report["violation_rate"] == 0.5
    assert report["alerts"] == 1
    path = tmp_path / "slo.json"
    path.write_text(json.dumps(report))
    recs = regress.ingest_file(path)
    metrics = {r["metric"]: r for r in recs}
    assert metrics["slo/violation_rate"]["value"] == 0.5
    assert metrics["slo/worst_burn"]["value"] == report["worst_burn_rate"]
    assert metrics["slo/alerts"]["value"] == 1.0
    assert all(r["kind"] == "slo" for r in recs)
    # roundtrip through a history file and gate a matching fresh epoch
    hist = tmp_path / "history.jsonl"
    for i in range(3):
        epoch = [dict(r, source=f"epoch{i}") for r in recs]
        regress.append_history(epoch, hist)
    verdicts = regress.check_records(recs, regress.load_history(hist))
    assert all(v["status"] in ("ok", "fast") for v in verdicts)


# -- the embedded HTTP plane ------------------------------------------------

def test_live_server_endpoints():
    agg = obs_live.LiveAggregator(slos=(SLO(),))
    agg.on_counter("serve.served", 2)
    with obs_export.LiveServer(agg, port=0) as ls:
        body = urllib.request.urlopen(ls.url + "/metrics").read().decode()
        assert "gauss_serve_served_total 2" in body
        health = json.loads(urllib.request.urlopen(
            ls.url + "/healthz").read().decode())
        assert health["status"] == "ok" and health["slo_firing"] == 0
        slo = json.loads(urllib.request.urlopen(
            ls.url + "/slo").read().decode())
        assert slo["slo"][0]["name"] == "serve_ok"
        snap = json.loads(urllib.request.urlopen(
            ls.url + "/snapshot").read().decode())
        assert snap["counters"]["serve.served"] == 2
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(ls.url + "/nope")
        assert ei.value.code == 404


def test_server_metrics_totals_match_requests(live_server, rng):
    agg = live_server.live
    before = agg.snapshot()["counters"]
    ok0 = before.get("serve.served", 0)
    for n in (12, 20, 12):
        a, b = _system(rng, n)
        res = live_server.solve(a, b)
        assert res.ok
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        flat = {name: v for name, labels, v in obs_top.parse_metrics(
            urllib.request.urlopen(
                live_server.live_url + "/metrics").read().decode())
            if not labels}
        if flat.get("gauss_serve_served_total", 0) >= ok0 + 3:
            break
        time.sleep(0.05)
    assert flat["gauss_serve_served_total"] == ok0 + 3
    assert "gauss_serve_latency_s_count" in flat
    assert flat.get("gauss_serve_queue_depth", 0) == 0


def test_on_demand_trace_capture_from_running_server(live_server, rng):
    url = live_server.live_url
    got = {}

    def grab():
        with urllib.request.urlopen(url + "/trace?batches=1&timeout=15",
                                    timeout=20) as resp:
            got["doc"] = json.loads(resp.read().decode())

    t = threading.Thread(target=grab)
    t.start()
    time.sleep(0.2)
    a, b = _system(rng, 12)
    assert live_server.solve(a, b).ok
    t.join(timeout=20)
    doc = got["doc"]
    spans = [ev for ev in doc["traceEvents"] if ev.get("ph") == "X"]
    names = {ev["name"] for ev in spans}
    assert "serve_batch_solve" in names
    solve = next(ev for ev in spans if ev["name"] == "serve_batch_solve")
    # the captured span carries request identity (the satellite bugfix)
    assert solve["args"].get("requests") == 1
    assert len(solve["args"].get("traces", [])) == 1
    assert doc["otherData"]["complete"] is True
    # bad query and double-arm behavior
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(url + "/trace?batches=zero")
    assert ei.value.code == 400


def test_gauss_top_once_smoke(live_server, capsys):
    rc = obs_top.main(["--url", live_server.live_url, "--once"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "gauss-top" in out and "requests:" in out and "cache:" in out
    rc = obs_top.main(["--url", live_server.live_url, "--once", "--json"])
    assert rc == 0
    samples = json.loads(capsys.readouterr().out)
    assert any(s["name"] == "gauss_serve_served_total" for s in samples)


def test_gauss_top_unreachable_endpoint_exits_2(capsys):
    rc = obs_top.main(["--url", "http://127.0.0.1:9", "--once"])
    assert rc == 2
    assert "cannot scrape" in capsys.readouterr().err


# -- trace_id propagation ---------------------------------------------------

def test_trace_propagation_batched_lane(live_server, rng):
    with obs.run(tool="trace_test") as rec:
        handles = []
        for _ in range(3):
            a, b = _system(rng, 12)
            handles.append(live_server.submit(a, b))
        results = [h.result(60) for h in handles]
    assert all(r.ok for r in results)
    trees = requesttrace.request_traces(rec.events)
    mine = [trees[h.trace_id] for h in handles]
    assert requesttrace.check_traces(
        {h.trace_id: t for h, t in zip(handles, mine)}) == []
    for tree in mine:
        stages = [s["stage"] for s in tree["stages"]]
        assert stages[0] == "serve_admit"
        assert "serve_batch" in stages
        assert "serve_batch_solve" in stages
        assert tree["status"] == "ok" and tree["lane"] == "batched"
        assert tree["terminal_count"] == 1
        # batch spans are shared records: members see the share count
        batch = next(s for s in tree["stages"]
                     if s["stage"] == "serve_batch_solve")
        assert batch.get("shared", 1) >= 1


def test_trace_propagation_retry_recovery_exactly_one_trace(rng,
                                                            monkeypatch):
    # Device lane poisoned with a transient error: the request must flow
    # admission -> retry -> numpy recovery lane, and the whole journey must
    # fold into EXACTLY ONE trace carrying the retry + recovery stages.
    server = SolverServer(_config(live_port=None, max_retries=1,
                                  unhealthy_after=1000))
    server.start()
    try:
        monkeypatch.setattr(
            server.cache, "get",
            lambda *a, **k: (_ for _ in ()).throw(
                RuntimeError("injected transient device error")))
        with obs.run(tool="trace_retry") as rec:
            a, b = _system(rng, 12)
            h = server.submit(a, b)
            res = h.result(60)
    finally:
        server.stop()
    assert res.ok and res.lane == "numpy"
    trees = requesttrace.request_traces(rec.events)
    assert list(trees) == [h.trace_id]  # exactly one trace, the request's
    tree = trees[h.trace_id]
    stages = [s["stage"] for s in tree["stages"]]
    assert "serve_admit" in stages
    assert "serve_retry" in stages          # the poisoned device attempts
    assert "serve_numpy" in stages          # the recovery lane, trace-bound
    assert tree["terminal_count"] == 1 and tree["status"] == "ok"
    assert requesttrace.check_traces(trees) == []


def test_recovery_rung_events_stamped_by_trace_context():
    # A rung-0 success emits no recovery noise by design; force the ladder
    # to escalate (singular system) and assert every emitted recovery rung
    # carries the surrounding trace context — the mechanism by which the
    # serve numpy lane's ladder lands inside the request's span tree.
    from gauss_tpu.resilience import recover

    a = np.zeros((4, 4))
    b = np.ones(4)
    with obs.run(tool="rung_trace") as rec:
        with obs.trace_context("rung-tid"):
            with pytest.raises(recover.UnrecoverableSolveError):
                recover.solve_resilient(a, b, rungs=("numpy_f64",))
    rungs = [ev for ev in rec.events if ev.get("type") == "recovery"]
    assert rungs and all(ev.get("trace") == "rung-tid" for ev in rungs)
    tree = requesttrace.request_traces(rec.events)["rung-tid"]
    assert "recovery" in [s["stage"] for s in tree["stages"]]


def test_trace_propagation_handoff_lane(rng):
    server = SolverServer(_config(live_port=None))
    server.start()
    try:
        with obs.run(tool="trace_handoff") as rec:
            a, b = _system(rng, 40)  # past the (16, 32) ladder top
            h = server.submit(a, b)
            res = h.result(120)
    finally:
        server.stop()
    assert res.ok and res.lane == "handoff"
    trees = requesttrace.request_traces(rec.events)
    tree = trees[h.trace_id]
    stages = [s["stage"] for s in tree["stages"]]
    assert "serve_handoff" in stages
    assert "route" in stages  # solve_handoff's decision, trace-stamped
    assert tree["terminal_count"] == 1


def test_rejected_and_expired_requests_carry_traces(rng):
    server = SolverServer(_config(live_port=None, max_queue=1))
    # NOT started: the queue fills and deadline requests expire untouched
    with obs.run(tool="trace_reject") as rec:
        a, b = _system(rng, 12)
        h1 = server.submit(a, b)              # occupies the queue
        h2 = server.submit(a, b)              # queue full -> rejected
        assert h2.result(5).status == "rejected"
        server.start()
        assert h1.result(30).ok
        server.stop()
    trees = requesttrace.request_traces(rec.events)
    assert trees[h2.trace_id]["status"] == "rejected"
    assert trees[h2.trace_id]["terminal_count"] == 1
    assert requesttrace.check_traces(trees) == []


def test_requesttrace_cli(tmp_path, capsys):
    path = tmp_path / "stream.jsonl"
    with obs.run(metrics_out=path, tool="cli_test"):
        obs.emit("serve_admit", id=1, trace="t1", n=8, queue_depth=1)
        obs.emit("serve_request", id=1, trace="t1", n=8, status="ok",
                 lane="batched", latency_s=0.01)
    rc = requesttrace.main([str(path), "--check"])
    assert rc == 0
    out = capsys.readouterr()
    assert "trace t1" in out.out and "status=ok" in out.out
    assert "0 problem(s)" in out.err
    rc = requesttrace.main([str(path), "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["t1"]["status"] == "ok"
    # a trace with no terminal fails --check
    with obs.run(metrics_out=path, tool="cli_test2"):
        obs.emit("serve_admit", id=2, trace="t2", n=8)
    assert requesttrace.main([str(path), "--check"]) == 1


# -- SLO-degraded shedding --------------------------------------------------

def test_slo_shed_degrades_admission_before_the_cliff(rng):
    server = SolverServer(_config(slo_shed=True, degraded_queue_factor=0.0))
    server.start()
    try:
        mon = server.live.slos[0]
        a, b = _system(rng, 12)
        assert server.solve(a, b).ok           # healthy: admitted
        mon.firing = True                      # force the alert state
        with obs.run(tool="shed_test") as rec:
            h = server.submit(a, b)
            res = h.result(5)
        assert res.status == "rejected"
        assert "slo degraded" in res.error
        ev = next(ev for ev in rec.events
                  if ev.get("type") == "serve_request"
                  and ev.get("id") == h.id)
        assert ev["reason"] == "slo_degraded"
        mon.firing = False                     # alert cleared: admitted again
        assert server.solve(a, b).ok
    finally:
        server.stop()


# -- loadgen + live plane ---------------------------------------------------

def test_loadgen_report_with_live_plane_includes_slo_and_retries(
        live_server):
    from gauss_tpu.serve.loadgen import (LoadgenConfig, format_summary,
                                         run_load)

    cfg = LoadgenConfig(mix="random:12*2,random:20", requests=8, warmup=2,
                        concurrency=2, seed=7, serve=live_server.config)
    summary = run_load(live_server, cfg)
    assert summary["counts"]["ok"] == 8 and summary["incorrect"] == 0
    assert summary["retries"] == 0
    slo = summary["slo"]
    assert slo["kind"] == "slo_report"
    assert slo["requests_counted"] >= 8
    text = format_summary(summary)
    assert "slo:" in text and "worst burn" in text


# -- summarize slo section --------------------------------------------------

def test_summarize_slo_alert_section(tmp_path, capsys):
    path = tmp_path / "alerts.jsonl"
    with obs.run(metrics_out=path, tool="slo_sum"):
        obs.emit("alert", slo="serve_ok", state="firing", burn_short=5.2,
                 burn_long=3.1)
        obs.emit("alert", slo="serve_ok", state="clear", burn_short=0.2,
                 burn_long=1.0)
    events = obs.read_events(path)
    sl = summarize.slo_summary(events)
    assert sl["alerts"] == 1 and sl["unresolved"] == 0
    assert sl["slos"]["serve_ok"]["worst_burn"] == 5.2
    assert summarize.main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "slo burn-rate alerts:" in out and "fired x1" in out
    assert summarize.main([str(path), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    (run_doc,) = doc.values()
    assert run_doc["slo"]["alerts"] == 1


# -- doctor: span-tree diff -------------------------------------------------

def _write_stream(path, tool, phases, repeat=1):
    with obs.run(metrics_out=path, tool=tool) as rec:
        for _ in range(repeat):
            for name, dur in phases:
                obs.record_span(name, dur)
    return rec.run_id


def test_doctor_attributes_regression_by_contribution(tmp_path):
    a_path = tmp_path / "r3.jsonl"
    b_path = tmp_path / "r5.jsonl"
    _write_stream(a_path, "bench_a",
                  [("factor", 0.0010), ("solve", 0.0003),
                   ("refine", 0.0002)])
    _write_stream(b_path, "bench_b",
                  [("factor", 0.0014), ("solve", 0.0003),
                   ("refine", 0.0002), ("host_hooks", 0.0004)])
    diff = doctor.diff_profiles(doctor.load_profile(str(a_path)),
                                doctor.load_profile(str(b_path)))
    assert diff["kind"] == "span_diff"
    np.testing.assert_allclose(diff["span_delta_s"], 0.0008, atol=1e-9)
    # sorted by regression contribution: the two slowdowns lead
    top2 = {p["phase"] for p in diff["phases"][:2]}
    assert top2 == {"factor", "host_hooks"}
    hooks = next(p for p in diff["phases"] if p["phase"] == "host_hooks")
    assert hooks["only_in"] == "b" and hooks["a_calls"] == 0
    factor = next(p for p in diff["phases"] if p["phase"] == "factor")
    np.testing.assert_allclose(factor["delta_s"], 0.0004, atol=1e-9)
    assert factor["share_of_delta"] == 0.5
    unchanged = next(p for p in diff["phases"] if p["phase"] == "solve")
    assert unchanged["delta_s"] == 0.0 and unchanged["only_in"] is None


def test_doctor_cli_text_json_and_run_selection(tmp_path, capsys):
    a_path = tmp_path / "a.jsonl"
    _write_stream(a_path, "t", [("factor", 0.001)])
    rid_b = _write_stream(a_path, "t", [("factor", 0.002)])  # same file
    rc = doctor.main([str(a_path), f"{a_path}:{rid_b}"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "span-tree diff" in out
    assert "biggest regression contributor: factor" in out
    out_json = tmp_path / "diff.json"
    rc = doctor.main([str(a_path), f"{a_path}:{rid_b}", "--json",
                      "-o", str(out_json)])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc == json.loads(out_json.read_text())
    assert doc["b"]["run"] == rid_b
    # bad inputs are typed, not tracebacks
    assert doctor.main([str(a_path) + ":nope", str(a_path)]) == 2
    assert doctor.main([str(tmp_path / "missing.jsonl"), str(a_path)]) == 2


# -- the hooks stay zero-cost when everything is off ------------------------

def test_hooks_noop_without_recorder_or_live_sink():
    # the module live_server fixture may hold the sink — detach it for the
    # duration so the disabled state is actually exercised
    prev = obs.set_live_sink(None)
    try:
        assert obs.active() is None and obs.live_sink() is None
        assert obs.emit("anything", x=1) is None
        obs.counter("nope")
        obs.gauge("nope", 1)
        obs.histogram("nope", 1)
        with obs.span("nope"):
            pass
        with obs.trace_context("tid"):
            assert obs.current_trace() == "tid"
            assert obs.emit("anything") is None
        assert obs.current_trace() is None
    finally:
        obs.set_live_sink(prev)


# -- the whole gate, end to end (the make live-check path) ------------------

@pytest.mark.slow
def test_livecheck_gate_end_to_end(tmp_path):
    from gauss_tpu.obs import livecheck

    rc = livecheck.main(["--requests", "16", "--burst", "8",
                         "--metrics-out", str(tmp_path / "live.jsonl"),
                         "--summary-json", str(tmp_path / "summary.json")])
    assert rc == 0
    summary = json.loads((tmp_path / "summary.json").read_text())
    assert summary["slo"]["alerts"] >= 1 and not summary["slo"]["firing"]
    assert summary["traces"] >= 16
