"""Mesh serving plane tests (ISSUE 14): the LaneSet — per-device dispatch
lanes, sticky key-affinity placement, work stealing, continuous batching
with a deadline-aware formation window, SLO-driven lane autoscaling — plus
the satellites: the process-shared default ExecutableCache with coalesced
builds (two lanes warming one bucket compile once), the per-lane-set
retry-after, the loadgen mesh report block and lane-qualified history
tags, journal exactly-once across steals, the multi-lane throughput leg,
and the mesh_serve regress ingest.

All CPU; conftest forces 8 virtual devices, so real per-device placement
(and the width>1 NamedSharding slice path) is exercised in-process.
Servers here pass lane_warmup=False (the per-placement backend compiles
land lazily and stay in the process-wide jit cache across tests) and
share LADDER/max_batch so the compiled set stays small.
"""

import threading
import time

import numpy as np
import pytest

import jax

from gauss_tpu import obs
from gauss_tpu.obs import regress, summarize
from gauss_tpu.serve import (
    CacheKey,
    ExecutableCache,
    LaneSet,
    ServeConfig,
    SolverServer,
    compat_sig,
    shared_cache,
)
from gauss_tpu.serve import loadgen
from gauss_tpu.serve.cache import CacheView
from gauss_tpu.verify import checks

LADDER = (16, 32)


def _system(rng, n, k=None):
    a = rng.standard_normal((n, n))
    a[np.arange(n), np.arange(n)] += float(n)
    b = rng.standard_normal(n) if k is None else rng.standard_normal((n, k))
    return a, b


def _config(**over):
    kw = dict(ladder=LADDER, max_batch=4, panel=16, refine_steps=1,
              verify_gate=1e-4, lanes=2, lane_warmup=False,
              cb_window_s=0.01)
    kw.update(over)
    return ServeConfig(**kw)


# -- lane set basics -------------------------------------------------------

def test_multi_lane_serve_end_to_end(rng):
    """lanes=N serves and verifies mixed-bucket traffic; every request
    resolves OK, the lane stats account for all served requests, and the
    single-lane path is untouched when lanes=0."""
    with SolverServer(_config(lanes=2)) as srv:
        assert srv._lanes is not None and srv._worker is None
        handles = []
        for i in range(12):
            a, b = _system(rng, [8, 12, 16, 24][i % 4])
            handles.append((a, b, srv.submit(a, b)))
        for a, b, h in handles:
            res = h.result(120)
            assert res.ok, (res.status, res.error)
            assert checks.residual_norm(a, res.x, b, relative=True) <= 1e-4
        st = srv.lane_stats()
        assert st["lanes"] == 2
        assert sum(p["served"] for p in st["per_lane"]) == 12
    # lanes=0 (default): the pre-mesh single-worker path, no LaneSet.
    with SolverServer(_config(lanes=0)) as srv:
        assert srv._lanes is None and srv._worker is not None
        assert srv.lane_stats() is None


def test_affinity_spreads_distinct_sigs(rng):
    """Sticky first-seen placement: distinct compat signatures land on
    distinct lanes (round-robin), and repeats stick to their lane."""
    with SolverServer(_config(lanes=2, continuous_batching=False)) as srv:
        ls = srv._lanes
        a16, b16 = _system(rng, 12)   # bucket 16
        a32, b32 = _system(rng, 24)   # bucket 32
        srv.solve(a16, b16)
        srv.solve(a32, b32)
        srv.solve(a16, b16)
        sigs = list(ls._sig_lane.items())
        assert len(sigs) == 2
        assert {idx for _, idx in sigs} == {0, 1}  # spread, not collided


def test_work_stealing_under_skew(rng):
    """All traffic shares ONE sig (affinity floods one lane); a burst
    deeper than the hot lane's batch slot must engage the sibling's
    steal path, and everything still serves exactly once."""
    with SolverServer(_config(lanes=2, max_batch=2,
                              continuous_batching=False)) as srv:
        systems = [_system(rng, 12) for _ in range(16)]
        handles = [srv.submit(a, b) for a, b in systems]
        for (a, b), h in zip(systems, handles):
            res = h.result(120)
            assert res.ok
            assert checks.residual_norm(a, res.x, b, relative=True) <= 1e-4
        st = srv.lane_stats()
        assert st["steals"] >= 1
        assert sum(p["stolen_in"] for p in st["per_lane"]) == \
            sum(p["stolen_out"] for p in st["per_lane"])
        assert sum(p["served"] for p in st["per_lane"]) == 16


def test_oversized_routes_handoff_in_mesh_mode(rng):
    """Past the ladder top a request dispatches solo through the handoff
    lane — compat_sig is None, never co-batched."""
    a, b = _system(rng, 48)  # > LADDER[-1] = 32
    with SolverServer(_config(lanes=2)) as srv:
        res = srv.solve(a, b, timeout=300)
        assert res.ok and res.lane in ("handoff", "fleet")

    class _Req:
        n = 48
        dtype = None
        structure = None

    assert compat_sig(_Req(), LADDER) is None


def test_stop_rejects_lane_leftovers(rng):
    """A non-drain stop refuses queued lane work with exactly one
    'rejected' terminal per request — nothing hangs, nothing doubles."""
    srv = SolverServer(_config(lanes=2, continuous_batching=False,
                               batch_linger_s=0.5, max_batch=2))
    srv.start()
    handles = [srv.submit(*_system(rng, 12)) for _ in range(8)]
    srv.stop(drain=False, timeout=5.0)
    statuses = [h.result(30).status for h in handles]
    assert all(s in ("ok", "rejected") for s in statuses)
    assert len(statuses) == 8


# -- shared cache + coalesced builds (satellite) ----------------------------

def test_default_cache_is_process_shared():
    s1 = SolverServer(_config(lanes=0))
    s2 = SolverServer(_config(lanes=0))
    assert s1.cache is s2.cache is shared_cache()
    # Explicit cache= keeps isolation (the pre-PR-14 behavior on request).
    s3 = SolverServer(_config(lanes=0), cache=ExecutableCache(8))
    assert s3.cache is not s1.cache
    # Capacity only grows.
    cap0 = shared_cache().capacity
    assert shared_cache(cap0 + 7).capacity == cap0 + 7
    assert shared_cache(4).capacity == cap0 + 7


def test_racing_warmups_compile_once():
    """Two lanes warming the same bucket pay ONE build: concurrent get()
    misses on one key coalesce — a single builder call, the waiter counts
    as a hit (it never compiled)."""
    cache = ExecutableCache(8)
    built = []
    gate = threading.Event()

    def slow_builder(key):
        built.append(key)
        gate.wait(5.0)  # hold the build so the second get must coalesce
        return object()

    key = CacheKey(bucket_n=16, nrhs=1, batch=4, dtype="float32",
                   engine="blocked", refine_steps=1)
    views = [CacheView(cache), CacheView(cache)]
    got = [None, None]

    def warm(i):
        got[i] = cache.get(key, builder=slow_builder)
        views[i].warmed.add(key)

    t1 = threading.Thread(target=warm, args=(0,))
    t2 = threading.Thread(target=warm, args=(1,))
    t1.start()
    t2.start()
    time.sleep(0.2)       # let both reach the build/coalesce point
    gate.set()
    t1.join()
    t2.join()
    assert len(built) == 1                  # ONE compile
    assert got[0] is got[1]                 # both lanes share the entry
    assert cache.misses == 1 and cache.coalesced >= 1
    assert views[0].warmed == views[1].warmed == {key}


def test_failed_build_releases_coalesce_slot():
    """A failing build propagates to its caller and lets the next caller
    retry instead of deadlocking the key."""
    cache = ExecutableCache(8)
    key = CacheKey(bucket_n=16, nrhs=1, batch=1, dtype="float32",
                   engine="blocked", refine_steps=1)
    with pytest.raises(RuntimeError):
        cache.get(key, builder=lambda k: (_ for _ in ()).throw(
            RuntimeError("boom")))
    sentinel = object()
    assert cache.get(key, builder=lambda k: sentinel) is sentinel


# -- continuous batching ---------------------------------------------------

def test_cb_admission_joins_inflight_batch(rng):
    """Requests arriving while a slot forms join IN-FLIGHT instead of
    waiting out a drain cycle: sequential submits inside one generous
    window co-batch into a single dispatch."""
    with SolverServer(_config(lanes=1, cb_window_s=0.5)) as srv:
        batches0 = srv.batches
        systems = [_system(rng, 12) for _ in range(4)]
        handles = []
        for a, b in systems:
            handles.append(srv.submit(a, b))
            time.sleep(0.02)    # arrivals spread across the window
        for h in handles:
            assert h.result(120).ok
        assert srv.batches - batches0 == 1          # ONE batch
        assert srv.lane_stats()["cb_admits"] >= 3   # joined the slot


def test_cb_formation_deadline_fires_partial(rng):
    """An unfilled slot dispatches at its formation deadline — latency is
    window-bounded, not company-bounded."""
    with SolverServer(_config(lanes=1, cb_window_s=0.05,
                              max_batch=8)) as srv:
        a, b = _system(rng, 12)
        assert srv.solve(a, b, timeout=300).ok  # untimed: compiles land
        t0 = time.perf_counter()
        res = srv.solve(a, b, timeout=120)
        elapsed = time.perf_counter() - t0
        assert res.ok
        assert elapsed < 2.0    # window + dispatch, not an 8-wide wait


def test_cb_deadline_aware_close(rng):
    """The slot closes BEFORE a member's request deadline: with a window
    far past the deadline, the request still serves (a blind linger
    would expire it — the fixed-drain A/B delta meshcheck gates)."""
    cfg = _config(lanes=1, cb_window_s=2.0, cb_deadline_margin_s=0.05,
                  max_batch=8)
    with SolverServer(cfg) as srv:
        a, b = _system(rng, 12)
        res = srv.submit(a, b, deadline_s=0.4).result(120)
        assert res.ok, (res.status, res.error)
        # And the blind discipline really does expire it:
    fixed = _config(lanes=1, continuous_batching=False,
                    batch_linger_s=2.0, max_batch=8)
    with SolverServer(fixed) as srv:
        a, b = _system(rng, 12)
        res = srv.submit(a, b, deadline_s=0.4).result(120)
        assert res.status == "expired"


def test_heterogeneous_arrivals_never_cobatch(rng):
    """dtype- and structure-heterogeneous requests never share a slot or
    an executable: same bucket, different sigs, separate batches."""
    cache = ExecutableCache(8)
    with SolverServer(_config(lanes=1, cb_window_s=0.3, refine_steps=2),
                      cache=cache) as srv:
        batches0 = srv.batches
        systems = [_system(rng, 12) for _ in range(4)]
        handles = []
        for i, (a, b) in enumerate(systems):
            handles.append(
                srv.submit(a, b, dtype="bfloat16" if i % 2 else None))
            time.sleep(0.02)
        for (a, b), h in zip(systems, handles):
            res = h.result(120)
            assert res.ok
            assert checks.residual_norm(a, res.x, b, relative=True) <= 1e-4
        assert srv.batches - batches0 == 2   # one per dtype, never mixed
        dtypes = {k.dtype for k in cache.keys()}
        assert dtypes == {"float32", "bfloat16"}


def test_journal_exactly_once_across_steal(rng, tmp_path):
    """Stealing a journaled request across lanes moves WHERE it computes,
    never how many terminals it gets: every admit holds exactly one
    journaled terminal, and the steal path demonstrably engaged."""
    from gauss_tpu.serve import durable

    jd = str(tmp_path / "journal")
    cfg = _config(lanes=2, max_batch=2, continuous_batching=False,
                  journal_dir=jd, journal_fsync_batch=1)
    with SolverServer(cfg) as srv:
        systems = [_system(rng, 12) for _ in range(16)]
        handles = [srv.submit(a, b) for a, b in systems]
        for h in handles:
            assert h.result(120).ok
        steals = srv.lane_stats()["steals"]
    assert steals >= 1
    state = durable.scan(jd)
    assert len(state.admits) == 16
    assert set(state.terminals) == set(state.admits)    # exactly once
    assert all(doc.get("status") == "ok"
               for doc in state.terminals.values())
    assert state.clean_shutdown


# -- retry-after (satellite) -----------------------------------------------

def test_retry_after_uses_lane_set_rate(rng):
    """The hint divides by the ACTIVE lanes' aggregate drain rate — the
    single-lane formula over-estimates the wait N-fold under multi-lane
    drain."""
    with SolverServer(_config(lanes=2)) as srv:
        ls = srv._lanes
        for lane in ls.lanes:
            lane.drain_rate = 50.0
        assert ls.drain_rate() == pytest.approx(100.0)
        # max_batch=4 over 100 req/s aggregate:
        assert srv.retry_after_hint() == pytest.approx(0.04)
        # the single-lane formula with one lane's rate would say 0.08
        ls.lanes[1].drain_rate = 0.0
        assert srv.retry_after_hint() == pytest.approx(0.08)


# -- width > 1: mesh slices -------------------------------------------------

def test_lane_width_shards_batch_axis(rng):
    """lane_width=2 lanes own a 2-device slice: a slot divisible by the
    width dispatches with a batch-axis NamedSharding, a non-divisible one
    falls back to the slice's first device — and solves verify either
    way."""
    with SolverServer(_config(lanes=2, lane_width=2)) as srv:
        lane = srv._lanes.lanes[0]
        assert len(lane.devices) == 2 and lane.mesh is not None
        sharded = lane.placement_for(4)
        assert isinstance(sharded, jax.sharding.NamedSharding)
        assert lane.placement_for(3) == lane.devices[0]
        a, b = _system(rng, 12)
        res = srv.solve(a, b, timeout=300)
        assert res.ok
        assert checks.residual_norm(a, res.x, b, relative=True) <= 1e-4


def test_lane_slices_partition():
    from gauss_tpu.dist import mesh as _mesh

    devs = jax.devices()
    assert len(_mesh.lane_slices(devs, 1)) == len(devs)
    pairs = _mesh.lane_slices(devs, 2)
    assert len(pairs) == len(devs) // 2
    assert all(len(p) == 2 for p in pairs)
    with pytest.raises(ValueError):
        _mesh.lane_slices(devs, len(devs) + 1)
    m = _mesh.lane_mesh(pairs[0])
    assert m.axis_names == ("batch",) and m.devices.size == 2


# -- autoscaling -----------------------------------------------------------

def test_autoscale_grows_on_burn_and_shrinks_quiet(rng):
    """A firing SLO alert grows the active lane count; a quiet period
    shrinks it back to min_lanes. Placement targets active lanes only."""
    firing = {"on": False}
    cfg = _config(lanes=3, autoscale=True, min_lanes=1,
                  autoscale_interval_s=0.0, autoscale_quiet_s=0.05)
    with obs.run() as rec:
        with SolverServer(cfg) as srv:
            ls = srv._lanes
            ls._slo_firing = lambda: firing["on"]
            assert ls.active_count() == 1
            firing["on"] = True
            for _ in range(100):
                if ls.active_count() == 3:
                    break
                time.sleep(0.02)
            assert ls.active_count() == 3
            firing["on"] = False
            for _ in range(100):
                if ls.active_count() == 1:
                    break
                time.sleep(0.02)
            assert ls.active_count() == 1
            # still serves while scaled down
            a, b = _system(rng, 12)
            assert srv.solve(a, b, timeout=120).ok
    scale = [e for e in rec.events if e["type"] == "lane_scale"]
    assert any(e["event"] == "grow" and e["reason"] == "slo_burn"
               for e in scale)
    assert any(e["event"] == "shrink" for e in scale)


# -- loadgen report + history tag (satellite) -------------------------------

def test_loadgen_mesh_block_and_lane_tag(rng, tmp_path):
    cfg = _config(lanes=2)
    lg = loadgen.LoadgenConfig(mix="random:10*2,random:20", requests=8,
                               warmup=2, concurrency=2, seed=7, serve=cfg)
    with SolverServer(cfg) as srv:
        with obs.run():
            summary = loadgen.run_load(srv, lg)
    assert summary["counts"]["ok"] == 8 and summary["incorrect"] == 0
    mesh = summary["mesh"]
    assert mesh["lanes"] == 2 and len(mesh["per_lane"]) == 2
    assert sum(p["served"] for p in mesh["per_lane"]) >= 8
    assert "mesh: 2 lane(s)" in loadgen.format_summary(summary)
    # Lane-qualified history tag: mesh epochs never pollute the
    # single-lane serve-check band.
    recs = loadgen.history_records(summary)
    assert recs and all(m.startswith("serve:closed:l2/") for m, _ in recs)
    out = tmp_path / "mesh_loadgen.json"
    loadgen.write_summary(summary, out)
    ingested = regress.ingest_file(out)
    assert any(r["metric"] == "serve:closed:l2/s_per_request"
               for r in ingested)


# -- obs: summarize + top ---------------------------------------------------

def test_summarize_serving_mesh_section(rng):
    with obs.run() as rec:
        with SolverServer(_config(lanes=2, max_batch=2,
                                  continuous_batching=False)) as srv:
            handles = [srv.submit(*_system(rng, 12)) for _ in range(12)]
            for h in handles:
                assert h.result(120).ok
    sv = summarize.serving_summary(rec.events)
    assert sv["mesh"]["lane_batches"]
    assert sum(sv["mesh"]["lane_batches"].values()) >= 1
    text = summarize.summarize_events(rec.events)
    assert "mesh: batches by lane" in text


def test_top_renders_lane_panel():
    from gauss_tpu.obs import top as _top

    text = "\n".join([
        "gauss_serve_served_total 12",
        "gauss_serve_lanes_active 2",
        "gauss_serve_steals_total 3",
        "gauss_serve_cb_admits_total 7",
        "gauss_serve_lane0_queue_depth 1",
        "gauss_serve_lane0_served 8",
        "gauss_serve_lane0_occupancy 0.75",
        "gauss_serve_lane1_served 4",
        "gauss_serve_lane1_stolen 4",
    ])
    frame = _top.render(_top._View(_top.parse_metrics(text)), "http://x")
    assert "mesh: 2 active lane(s), steals 3" in frame
    assert "lane 0: depth 1, served 8" in frame
    assert "lane 1:" in frame and "stolen 4" in frame


# -- throughput multi-lane leg + mesh_serve ingest --------------------------

def test_throughput_multilane_leg(tmp_path):
    from gauss_tpu.bench import throughput

    with obs.run():
        summary = throughput.measure_throughput(
            ns=[16], batch=2, reps=1, seed=3, lanes=2)
    leg = summary["legs"][0]
    assert leg["lanes"] == 2 and leg["verified"]
    recs = throughput.history_records(summary)
    assert recs and recs[0][0] == "tput:float32/n16/b2/l2/s_per_solve"
    assert "lanes=2" in throughput.format_summary(summary)
    # single-lane metric names are untouched
    with obs.run():
        single = throughput.measure_throughput(ns=[16], batch=2, reps=1,
                                               seed=3)
    assert throughput.history_records(single)[0][0] == \
        "tput:float32/n16/b2/s_per_solve"


def test_meshcheck_history_and_ingest(tmp_path):
    from gauss_tpu.serve import meshcheck

    summary = {
        "kind": "mesh_serve",
        "smoke": {"throughput_rps": 100.0,
                  "latency_s": {"p95": 0.02}},
        "ab": {"cb_throughput_rps": 40.0, "fixed_over_cb": 0.5},
    }
    recs = dict((m, v) for m, v, _ in meshcheck.history_records(summary))
    assert recs["mesh:smoke/s_per_request"] == pytest.approx(0.01)
    assert recs["mesh:smoke/p95_s"] == pytest.approx(0.02)
    assert recs["mesh:ab/cb_s_per_request"] == pytest.approx(0.025)
    assert recs["mesh:ab/fixed_over_cb"] == pytest.approx(0.5)
    out = tmp_path / "mesh.json"
    import json

    out.write_text(json.dumps(summary))
    ingested = regress.ingest_file(out)
    assert {r["metric"] for r in ingested} == set(recs)
    assert all(r["kind"] == "mesh_serve" for r in ingested)


def test_committed_mesh_epochs_present():
    """The 3 seeded epochs the gate baselines against are committed."""
    hist = regress.load_history(regress.default_history_path())
    for metric in ("mesh:smoke/s_per_request", "mesh:ab/fixed_over_cb",
                   "tput:float32/n256/b8/l4/s_per_solve"):
        assert len([r for r in hist
                    if r.get("metric") == metric]) >= 3, metric
    assert "tput:float32/n256/b8/l4/s_per_solve" in regress.RATCHET_BASELINES
