"""Network-tier tests: the wire codec (result docs, slab framing, the
full-jitter backoff policy), the replica HTTP endpoint end to end
(solve, idempotent resubmission, chunked upload, Retry-After on
overload), journal adoption on a surviving peer (typed STATUS_EXPIRED
for deadline-dead admits, resubmit-racing-replay dedupe), the router's
consistent-hash ring and durable assignment log, and the obs wiring
(``kind: replica_campaign`` regress ingest, the summarizer's replica
section, the loadgen ``serve:net:`` history tag).

All CPU (conftest pins the platform); servers share one module-scoped
executable cache so the jitted batch executables compile once.
"""

import json
import os
import random
import threading
import time
import urllib.request

import numpy as np
import pytest

from gauss_tpu.obs import regress, summarize
from gauss_tpu.serve import (
    STATUS_EXPIRED,
    STATUS_OK,
    ServeConfig,
    SolverServer,
    durable,
    loadgen,
    net,
)
from gauss_tpu.serve.cache import ExecutableCache
from gauss_tpu.serve.router import AssignLog, HashRing
from gauss_tpu.verify import checks

GATE = 1e-4


@pytest.fixture(scope="module")
def shared_cache():
    return ExecutableCache(64)


@pytest.fixture()
def rng():
    return np.random.default_rng(190733)


def _system(rng, n):
    a = rng.standard_normal((n, n))
    a[np.arange(n), np.arange(n)] += float(n)
    return a, rng.standard_normal(n)


def _config(journal_dir=None, **over):
    kw = dict(ladder=(16, 32), max_batch=4, panel=16, refine_steps=1,
              verify_gate=GATE, journal_dir=journal_dir,
              journal_fsync_batch=4)
    kw.update(over)
    return ServeConfig(**kw)


# -- wire codec ------------------------------------------------------------

def test_result_doc_roundtrip(rng):
    x = rng.standard_normal(7)
    from gauss_tpu.serve.admission import ServeResult

    original = ServeResult(status=STATUS_OK, x=x, lane="batched",
                           bucket_n=16, trace="t-1", latency_s=0.5,
                           queue_s=0.1, rel_residual=1e-9,
                           device_s=0.01, compile_s=0.2)
    doc = net.result_doc(original)
    assert doc["schema"] == net.WIRE_SCHEMA
    back = net.doc_result(json.loads(json.dumps(doc)))  # through the wire
    assert back.status == STATUS_OK and back.lane == "batched"
    assert back.bucket_n == 16 and back.trace == "t-1"
    assert back.rel_residual == pytest.approx(1e-9)
    assert back.device_s == pytest.approx(0.01)
    np.testing.assert_allclose(back.x, x)

    none_doc = net.result_doc(ServeResult(status="rejected",
                                          retry_after_s=0.4))
    assert "x" not in none_doc
    assert net.doc_result(none_doc).x is None


def test_full_jitter_backoff_bounds():
    r = random.Random(7)
    for attempt in range(12):
        ceiling = min(30.0, 0.05 * 2 ** attempt)
        for _ in range(20):
            v = net.full_jitter_backoff(0.05, attempt, rng=r)
            assert 0.0 <= v <= ceiling
    # the cap bounds late attempts
    assert all(net.full_jitter_backoff(1.0, 50, rng=r, cap_s=2.0) <= 2.0
               for _ in range(50))


def test_slab_framing_covers_and_counts(rng):
    a = rng.standard_normal((37, 5))
    target = 200  # bytes — forces many slabs at this shape
    slabs = list(net.iter_slabs(a, target_bytes=target))
    assert [s[0] for s in slabs] == list(range(len(slabs)))
    assert len(slabs) == net.slab_count(37, 5, a.dtype.itemsize,
                                        target_bytes=target)
    rebuilt = np.vstack([rows for _, _, _, rows in slabs])
    np.testing.assert_array_equal(rebuilt, a)
    # slab boundaries tile [0, n) without gap or overlap
    edges = [(r0, r1) for _, r0, r1, _ in slabs]
    assert edges[0][0] == 0 and edges[-1][1] == 37
    assert all(p[1] == q[0] for p, q in zip(edges, edges[1:]))


def test_matrix_digest_is_content_keyed(rng):
    a = rng.standard_normal((6, 6))
    assert net.matrix_digest(a) == net.matrix_digest(a.copy())
    assert net.matrix_digest(a) != net.matrix_digest(a + 1e-9)


# -- the replica HTTP endpoint --------------------------------------------

def test_http_solve_e2e_idempotent_resubmit(tmp_path, rng, shared_cache):
    srv = SolverServer(_config(str(tmp_path / "journal")),
                       cache=shared_cache)
    srv.start()
    api = net.RequestApi(net.ReplicaApp(srv)).start()
    try:
        client = net.SolveClient(api.url, seed=3)
        a, b = _system(rng, 12)
        res = client.solve(a, b, request_id="e2e-1", timeout=60)
        assert res.status == STATUS_OK
        assert checks.residual_norm(a, res.x, b, relative=True) <= GATE
        served = srv.requests_served
        # resubmitting the SAME idempotency key resolves from the journal
        # without a second solve (a fresh trace is minted — the dedupe is
        # a new client interaction — but the solve count must not move)
        res2 = client.solve(a, b, request_id="e2e-1", timeout=60)
        assert res2.status == STATUS_OK
        assert srv.requests_served == served
        np.testing.assert_allclose(res2.x, res.x)
        # the async handle path
        h = client.submit(a, b, request_id="e2e-2")
        assert h.result(60).status == STATUS_OK
    finally:
        api.stop()
        srv.stop(drain=True)


def test_http_chunked_upload_solve(tmp_path, rng, shared_cache):
    srv = SolverServer(_config(str(tmp_path / "journal")),
                       cache=shared_cache)
    srv.start()
    api = net.RequestApi(net.ReplicaApp(srv)).start()
    try:
        # threshold 0: every operand goes through POST /v1/upload slabs
        client = net.SolveClient(api.url, upload_threshold=0, seed=5)
        a, b = _system(rng, 24)
        res = client.solve(a, b, timeout=60)
        assert res.status == STATUS_OK
        assert checks.residual_norm(a, res.x, b, relative=True) <= GATE
    finally:
        api.stop()
        srv.stop(drain=True)


def test_queue_full_503_carries_retry_after(rng):
    srv = SolverServer(_config(max_queue=1))  # worker NOT started
    api = net.RequestApi(net.ReplicaApp(srv)).start()
    try:
        a, b = _system(rng, 8)
        body = json.dumps({
            "schema": net.WIRE_SCHEMA, "wait_s": 0,
            "a": durable.encode_array(a),
            "b": durable.encode_array(b)}).encode()

        def _post():
            req = urllib.request.Request(
                api.url + "/v1/solve", data=body, method="POST",
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=10) as resp:
                    return resp.status, dict(resp.headers)
            except urllib.error.HTTPError as e:
                e.read()
                return e.code, dict(e.headers or {})

        codes = [_post() for _ in range(3)]
        # queued admits park (202); the over-bound one is shed with the
        # drain hint surfaced as an integer Retry-After header
        assert any(code == 202 for code, _ in codes)
        shed = [(code, hdrs) for code, hdrs in codes if code == 503]
        assert shed
        assert int(shed[-1][1]["Retry-After"]) >= 1
    finally:
        api.stop()
        srv.stop(drain=False)


def test_bad_schema_and_unknown_rid(rng):
    srv = SolverServer(_config(max_queue=4))
    app = net.ReplicaApp(srv)
    code, payload = app.handle_solve({"schema": 99})
    assert code == 400 and "schema" in payload["error"]
    assert app.lookup("no-such-rid") == (None, None)
    srv.stop(drain=False)


# -- journal adoption (failover replay on a surviving peer) ----------------

def test_adopt_expired_yields_typed_terminal(tmp_path, rng, shared_cache):
    """An admit whose deadline died during the failover window must
    resolve as STATUS_EXPIRED on the adopter — never a silent drop."""
    victim_dir = str(tmp_path / "victim")
    victim = SolverServer(_config(victim_dir))  # worker NOT started
    a, b = _system(rng, 10)
    victim.submit(a, b, deadline_s=0.05, request_id="dead-rid")
    victim.submit(a, b, request_id="live-rid")
    victim._crash()
    time.sleep(0.1)  # the 50 ms deadline expires before adoption

    survivor = SolverServer(_config(str(tmp_path / "survivor")),
                            cache=shared_cache)
    survivor.start()
    try:
        out = net.adopt_journal(survivor, victim_dir)
        assert out["expired"] == 1 and out["replayed"] == 1
        t0 = time.monotonic()
        while (time.monotonic() - t0 < 60
               and not {"dead-rid", "live-rid"}
               <= set(survivor._rid_terminals)):
            time.sleep(0.01)
        dead = survivor._rid_terminals["dead-rid"]
        assert dead["status"] == STATUS_EXPIRED
        live = survivor._rid_terminals["live-rid"]
        assert live["status"] == STATUS_OK
        x = durable.decode_array(live["x"]).reshape(-1)
        assert checks.residual_norm(a, x, b, relative=True) <= GATE
    finally:
        survivor.stop(drain=True)


def test_resubmit_racing_replay_dedupes(tmp_path, rng, shared_cache):
    """A client resubmission that lands on the adopter BEFORE the replay
    folds the victim's journal must end with exactly one terminal for the
    rid — the replay skips the already-owned key."""
    victim_dir = str(tmp_path / "victim")
    victim = SolverServer(_config(victim_dir))  # worker NOT started
    a, b = _system(rng, 10)
    victim.submit(a, b, request_id="raced-rid")
    victim._crash()

    survivor_dir = str(tmp_path / "survivor")
    survivor = SolverServer(_config(survivor_dir), cache=shared_cache)
    survivor.start()
    try:
        # the storm side wins the race: resubmit before adoption
        h = survivor.submit(a, b, request_id="raced-rid")
        out = net.adopt_journal(survivor, victim_dir)
        assert out["skipped"] == 1 and out["replayed"] == 0
        assert h.result(60).status == STATUS_OK
    finally:
        survivor.stop(drain=True)
    # exactly one terminal for the rid across the survivor's raw records
    terminals = []
    for seg in durable.segment_paths(survivor_dir):
        with open(seg, "rb") as f:
            for line in f.read().split(b"\n"):
                if not line:
                    continue
                doc = durable.decode_line(line + b"\n")
                if (doc and doc.get("rec") == "terminal"
                        and doc.get("rid") == "raced-rid"):
                    terminals.append(doc)
    assert len(terminals) == 1 and terminals[0]["status"] == STATUS_OK


def test_adopt_concurrent_resubmit_storm(tmp_path, rng, shared_cache):
    """Resubmits racing the replay FROM THREADS: one terminal per rid,
    no double solve (the depth-lock critical section both sides admit
    under)."""
    victim_dir = str(tmp_path / "victim")
    victim = SolverServer(_config(victim_dir))
    systems = [_system(rng, 10) for _ in range(4)]
    for j, (a, b) in enumerate(systems):
        victim.submit(a, b, request_id=f"storm-{j}")
    victim._crash()

    survivor = SolverServer(_config(str(tmp_path / "survivor")),
                            cache=shared_cache)
    survivor.start()
    results = {}

    def _storm(j):
        a, b = systems[j]
        results[j] = survivor.solve(a, b, request_id=f"storm-{j}",
                                    timeout=60)

    try:
        threads = [threading.Thread(target=_storm, args=(j,))
                   for j in range(4)]
        adopter = threading.Thread(
            target=net.adopt_journal, args=(survivor, victim_dir))
        for t in threads + [adopter]:
            t.start()
        for t in threads + [adopter]:
            t.join(120)
        assert all(results[j].status == STATUS_OK for j in range(4))
    finally:
        survivor.stop(drain=True)
    counts = {f"storm-{j}": 0 for j in range(4)}
    for seg in durable.segment_paths(str(tmp_path / "survivor")):
        with open(seg, "rb") as f:
            for line in f.read().split(b"\n"):
                if not line:
                    continue
                doc = durable.decode_line(line + b"\n")
                if (doc and doc.get("rec") == "terminal"
                        and doc.get("rid") in counts):
                    counts[doc["rid"]] += 1
    assert all(v == 1 for v in counts.values()), counts


# -- the router's ring and assignment log ----------------------------------

def test_hashring_stability_under_death():
    ring = HashRing(["r0", "r1", "r2"])
    keys = [f"key-{i}" for i in range(300)]
    before = {k: ring.lookup(k) for k in keys}
    assert set(before.values()) == {"r0", "r1", "r2"}
    survivors = {"r0", "r2"}
    after = {k: ring.lookup(k, live=survivors) for k in keys}
    moved = sum(1 for k in keys if before[k] != after[k])
    # only the dead node's arc moves (~1/3), and it moves ENTIRELY
    assert all(after[k] in survivors for k in keys)
    assert all(before[k] == after[k] for k in keys
               if before[k] != "r1")
    assert moved == sum(1 for k in keys if before[k] == "r1")
    assert 0 < moved < len(keys)
    # the adopter choice is the dead node's ring successor, in survivors
    assert ring.lookup("r1", live=survivors) in survivors


def test_assignlog_replay_failover_torn_tail(tmp_path):
    path = str(tmp_path / "assign.log")
    log = AssignLog(path)
    for i in range(12):
        log.assign(f"rid-{i}", f"r{i % 3}")
    moved = log.failover("r1", "r2")
    assert moved == 4
    pins = log.pins()
    log.close()
    assert set(pins.values()) == {"r0", "r2"}

    # reopen replays to the identical map
    log2 = AssignLog(path)
    assert log2.pins() == pins
    log2.close()

    # a torn tail drops ONLY the damaged record
    with open(path, "ab") as f:
        f.write(durable.encode_record(
            {"rec": "assign", "rid": "torn-rid", "node": "r0"})[:-4])
    log3 = AssignLog(path)
    got = log3.pins()
    log3.close()
    assert "torn-rid" not in got
    assert {k: v for k, v in got.items() if k != "torn-rid"} == pins


# -- obs wiring ------------------------------------------------------------

def test_loadgen_history_tag_net_qualified():
    base = {"mode": "closed", "throughput_rps": 10.0}
    plain = dict(loadgen.history_records(base))
    assert "serve:closed/s_per_request" in plain
    wired = dict(loadgen.history_records(dict(base, net="http://x")))
    assert "serve:net:closed/s_per_request" in wired
    assert "serve:closed/s_per_request" not in wired


def test_regress_ingests_replica_campaign(tmp_path):
    summary = {
        "kind": "replica_campaign", "seed": 1, "cases": 30,
        "tput": {"replicas_1": {"s_per_request": 0.12},
                 "replicas_3": {"s_per_request": 0.05}},
        "legs": [{"leg": "kill3", "recovery_s": [1.0, 2.0, 3.0]},
                 {"leg": "drain_free", "recovery_s": []}],
    }
    path = tmp_path / "summary.json"
    path.write_text(json.dumps(summary))
    recs = regress.ingest_file(str(path))
    by_metric = {r["metric"]: r for r in recs}
    assert by_metric["replica:s_per_request"]["value"] == \
        pytest.approx(0.05)
    assert by_metric["replica:failover_recovery_s"]["value"] == \
        pytest.approx(2.0)
    assert all(r["unit"] == "s" for r in recs)


def test_summarize_replica_section():
    evs = [
        {"type": "router", "event": "listening", "replicas": 3},
        {"type": "router", "event": "restart", "charged": True},
        {"type": "replica", "event": "listening"},
        {"type": "replica_failover", "cause": "killed", "pins_moved": 4,
         "replayed": 2, "imported": 3, "expired": 1, "recovery_s": 1.5},
        # case_violations carries the violating cases themselves on the
        # wire; the summary folds the list to a count.
        {"type": "replica_campaign", "cases": 30, "admitted": 200,
         "case_violations": [], "invariant_ok": True},
    ]
    rp = summarize.replica_summary(evs)
    assert rp["router_events"] == {"listening": 1, "restart": 1}
    fo = rp["failovers"]
    assert fo["count"] == 1 and fo["by_cause"] == {"killed": 1}
    assert fo["pins_moved"] == 4 and fo["replayed"] == 2
    assert fo["max_recovery_s"] == pytest.approx(1.5)
    camp = rp["campaign"]
    assert camp["invariant_ok"] and camp["case_violations"] == 0
    lines = summarize._replica_lines(rp)
    assert any("failovers: 1" in ln for ln in lines)
    # no replica traffic -> no section (the empty-dict contract)
    assert summarize.replica_summary(
        [{"type": "serve_request"}]) == {}
