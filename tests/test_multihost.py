"""Multi-host bootstrap tests: REAL multi-process collectives on localhost.

The reference validated its distributed engine only on an actual 6-node
cluster via the hostfile (SURVEY.md §4.5). The analog here launches two real
OS processes, each with 4 virtual CPU devices, joins them through
``multihost.initialize`` (gRPC coordination — the mpirun/hostfile analog),
and runs the row-cyclic distributed solve over the resulting 8-device global
pool. This exercises genuine cross-process collectives, not just the
single-process 8-device simulation the rest of the suite uses.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4").strip()
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")

from gauss_tpu.dist import multihost

multihost.initialize(coordinator={coord!r}, num_processes=2,
                     process_id={pid})
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()
assert multihost.is_multihost()
print(multihost.process_banner(), flush=True)

import numpy as np
from gauss_tpu import obs
from gauss_tpu.dist import gauss_dist, make_mesh
from gauss_tpu.io import synthetic
from gauss_tpu.verify import checks

# The multihost telemetry protocol under test: each process writes its OWN
# stream stamped with ONE shared run id (derived from the coordination
# address), exactly as cli._common.metrics_run does for real drivers.
stream, run_id = multihost.resolve_metrics_stream(
    {metrics!r}, coordinator={coord!r}, process_id={pid})

with obs.run(metrics_out=stream, run_id=run_id, tool="mh_worker"):
    n = 64
    with obs.span("initMatrix"):
        a = synthetic.internal_matrix(n, dtype=np.float32)
        b = synthetic.internal_rhs(n, dtype=np.float32)
    mesh = make_mesh(8)
    with obs.span("solve_dist"):
        x = np.asarray(gauss_dist.gauss_solve_dist(a, b, mesh=mesh),
                       np.float64)
    assert checks.internal_pattern_ok(x, atol=1e-3), x[:4]

    # The round-3 scaling engines over the SAME cross-process pool: the 1-D
    # panel-blocked factorization and the 2-D tournament-pivoted one — real
    # cross-process collectives through their per-panel psum/all_gather
    # protocol, not just the single-process simulation.
    from gauss_tpu.dist import gauss_dist_blocked, gauss_dist_blocked2d
    from gauss_tpu.dist.mesh import make_mesh_2d

    with obs.span("solve_dist_blocked"):
        xb = np.asarray(gauss_dist_blocked.gauss_solve_dist_blocked(
            a, b, mesh=mesh, panel=4), np.float64)
    assert checks.internal_pattern_ok(xb, atol=1e-3), xb[:4]

    mesh2 = make_mesh_2d(4, 2)
    with obs.span("solve_dist_blocked2d"):
        x2 = np.asarray(gauss_dist_blocked2d.gauss_solve_dist_blocked2d(
            a, b, mesh=mesh2, panel=4), np.float64)
    assert checks.internal_pattern_ok(x2, atol=1e-3), x2[:4]
print("RESULT_OK process {pid}", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_solve(tmp_path):
    coord = f"127.0.0.1:{_free_port()}"
    metrics = str(tmp_path / "mh.jsonl")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, "-c",
             _WORKER.format(repo=REPO, coord=coord, pid=pid,
                            metrics=metrics)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO)
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost processes timed out:\n" + "\n".join(outs))
    if any("Multiprocess computations aren't implemented on the CPU backend"
           in out for out in outs):
        # jaxlib releases without gloo-backed CPU cross-process collectives
        # can initialize the distributed runtime but cannot run the solve;
        # the capability is only discoverable by trying it.
        pytest.skip("this jaxlib's CPU backend lacks multiprocess collectives")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out}"
        assert f"RESULT_OK process {pid}" in out
        assert "local / 8 global devices" in out
    _check_multihost_telemetry(tmp_path)


def _check_multihost_telemetry(tmp_path):
    """The distributed-observability acceptance path, on REAL cross-process
    streams: two per-process JSONL files -> one merged run with per-process
    straggler stats -> a loadable Chrome trace with one lane per process."""
    import json

    from gauss_tpu.obs import aggregate, summarize, trace

    p0, p1 = str(tmp_path / "mh.p0.jsonl"), str(tmp_path / "mh.p1.jsonl")
    assert os.path.exists(p0) and os.path.exists(p1), \
        "each process must write its own stream"
    rid, merged = aggregate.merge_streams([p0, p1])
    procs = {ev["proc"] for ev in merged}
    assert procs == {0, 1}, procs
    # Both processes stamped the SAME derived run id.
    assert {ev["run"] for ev in merged} == {rid}
    stats = aggregate.straggler_stats(merged)
    assert stats["processes"] == [0, 1]
    solve = stats["phases"]["dist_factor_solve"]
    assert solve["max_s"] >= solve["min_s"] >= 0.0
    assert 0.0 <= solve["skew"] <= 1.0
    # Cross-process collective accounting made it into both streams.
    colls = [ev for ev in merged if ev["type"] == "collective"]
    assert {ev["proc"] for ev in colls} == {0, 1}
    assert any(ev["label"] == "gauss_dist_blocked" for ev in colls)
    # Per-lane coverage: two lanes, each with its own wall-clock.
    prof = summarize.flat_profile(merged)
    assert set(prof["lanes"]) == {0, 1}
    for lane in prof["lanes"].values():
        assert lane["wall_s"] and 0.0 < lane["coverage"] <= 1.05
    # Chrome-trace export: loadable JSON, one lane (pid) per process.
    out = tmp_path / "mh.trace.json"
    aggregate.write_merged(merged, tmp_path / "mh.merged.jsonl")
    assert trace.main([str(tmp_path / "mh.merged.jsonl"),
                       "-o", str(out)]) == 0
    doc = json.loads(out.read_text())
    pids = {ev["pid"] for ev in doc["traceEvents"] if ev["ph"] == "X"}
    assert pids == {0, 1}


def test_initialize_rejects_double_init_different_topology(monkeypatch):
    from gauss_tpu.dist import multihost

    monkeypatch.setattr(multihost, "_INITIALIZED", ("127.0.0.1:9", 2, 1))
    with pytest.raises(RuntimeError, match="already"):
        multihost.initialize("127.0.0.1:1", 1, 0)


def test_initialize_idempotent_same_topology(monkeypatch):
    """A repeated identical call is a no-op (MPI_Initialized-guarded
    MPI_Init semantics) — jax.distributed.initialize must NOT run again."""
    from gauss_tpu.dist import multihost

    for k in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
              "JAX_PROCESS_ID"):
        monkeypatch.delenv(k, raising=False)
    topo = ("127.0.0.1:9", 2, 1)
    monkeypatch.setattr(multihost, "_INITIALIZED", topo)

    import jax

    def boom(**kwargs):
        raise AssertionError("jax.distributed.initialize re-invoked")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    multihost.initialize(*topo)  # must return silently


def test_maybe_initialize_noop_without_coordinates(monkeypatch):
    from gauss_tpu.dist import multihost

    for k in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
              "JAX_PROCESS_ID"):
        monkeypatch.delenv(k, raising=False)

    class Args:
        coordinator = None
        num_processes = None
        process_id = None

    assert multihost.maybe_initialize_from_args(Args()) is False


def test_add_multihost_args_parses():
    import argparse

    from gauss_tpu.dist import multihost

    p = argparse.ArgumentParser()
    multihost.add_multihost_args(p)
    args = p.parse_args(["--coordinator", "h:1", "--num-processes", "2",
                         "--process-id", "1"])
    assert (args.coordinator, args.num_processes, args.process_id) == \
        ("h:1", 2, 1)
