"""gauss_tpu.structure: detector edge cases, engines, router, serving lanes.

The detector tests pin the ISSUE's edge-case list: near-SPD non-symmetric
input must NOT certify, a bandwidth-n matrix degenerates to dense, a
PERMUTED block-diagonal matrix must not be detected (falls back to dense
LU), empty/1x1 systems are handled, and ``solve_auto`` is bit-identical to
the direct engine on every structure class.
"""

import io

import numpy as np
import pytest

from gauss_tpu.io import synthetic
from gauss_tpu.structure import (
    StructureMismatchError,
    banded,
    blockdiag,
    cholesky,
    detect_structure,
    detect_structure_dat,
    solve_auto,
    structure_tag,
)
from gauss_tpu.verify import checks

GATE = 1e-4


def _rng(seed=0):
    return np.random.default_rng(seed)


# ---------------------------------------------------------------- detector

def test_detect_spd_certified():
    info = detect_structure(synthetic.spd_matrix(64))
    assert info.kind == "spd"
    assert info.symmetric and info.spd_likely
    assert len(info.blocks) == 1
    assert info.density == 1.0


def test_detect_near_spd_nonsymmetric_is_dense():
    a = synthetic.spd_matrix(48)
    a[0, 1] += 1e-9  # near-SPD, but not symmetric — must NOT certify
    info = detect_structure(a)
    assert not info.symmetric and not info.spd_likely
    assert info.kind == "dense"


def test_detect_banded_and_bandwidth_n_degenerates_dense():
    tri = synthetic.banded_matrix(64, 1)
    info = detect_structure(tri)
    assert info.kind == "banded" and info.bandwidth == 1
    # bandwidth ~n: structurally a band, but past the engine's advantage —
    # classifies dense (here: non-symmetric so not spd either)
    wide = synthetic.dense_matrix(64)
    info_w = detect_structure(wide)
    assert info_w.bandwidth == 63
    assert info_w.kind == "dense"


def test_detect_blockdiag_contiguous_only():
    a = synthetic.blockdiag_matrix(64, 8)
    info = detect_structure(a)
    assert info.kind == "blockdiag"
    assert info.blocks == (8,) * 8
    # a PERMUTED block-diagonal matrix must not be detected: the
    # contiguous partition is gone, and the router falls back to dense LU
    p = _rng(1).permutation(64)
    info_p = detect_structure(a[np.ix_(p, p)])
    assert info_p.kind == "dense"
    assert len(info_p.blocks) == 1


def test_detect_trivial_systems():
    assert detect_structure(np.zeros((0, 0))).kind == "dense"
    assert detect_structure(np.array([[3.0]])).kind == "dense"
    diag = detect_structure(np.diag(np.arange(1.0, 9.0)))
    assert diag.bandwidth == 0 and len(diag.blocks) == 8


def test_detect_dat_stream_matches_dense_scan():
    from gauss_tpu.io import datfile

    for a in (synthetic.spd_matrix(24), synthetic.banded_matrix(24, 2),
              synthetic.blockdiag_matrix(24, 6), synthetic.dense_matrix(24)):
        buf = io.StringIO()
        datfile.write_dat(buf, a, drop_zeros=True)
        buf.seek(0)
        assert detect_structure_dat(buf) == detect_structure(a)


# ----------------------------------------------------------------- engines

def test_cholesky_solves_and_types_non_spd():
    import jax.numpy as jnp

    a = synthetic.spd_matrix(48)
    b = _rng(2).standard_normal(48)
    x, fac = cholesky.solve_spd_refined(a, b)
    assert checks.residual_norm(a, x, b, relative=True) <= GATE
    assert float(np.asarray(fac.min_diag)) > 0
    # symmetric but indefinite: typed NotSPDError, never NaN out
    indef = a - 2.0 * np.eye(48)
    with pytest.raises(cholesky.NotSPDError):
        cholesky.cholesky_factor(jnp.asarray(indef, jnp.float32))


def test_cholesky_multi_rhs_and_ds():
    a = synthetic.spd_matrix(32)
    b = _rng(3).standard_normal((32, 3))
    x, _ = cholesky.solve_spd_refined(a, b)
    assert x.shape == (32, 3)
    assert checks.residual_norm(a, x, b, relative=True) <= GATE
    xd, _ = cholesky.solve_spd_ds(a, b[:, 0], iters=3)
    assert checks.residual_norm(a, xd, b[:, 0], relative=True) <= GATE


def test_banded_tridiag_scan_large():
    n = 2048
    a = synthetic.banded_matrix(n, 1)
    b = _rng(4).standard_normal(n)
    x = banded.solve_banded_refined(a, b, bandwidth=1, iters=2)
    assert checks.residual_norm(a, x, b, relative=True) <= GATE


def test_banded_block_lu_and_mismatch():
    a = synthetic.banded_matrix(96, 3)
    b = _rng(5).standard_normal(96)
    x = banded.solve_banded_refined(a, b, iters=2)
    assert checks.residual_norm(a, x, b, relative=True) <= GATE
    # a full matrix busts the band limit: typed, not slow-and-wrong
    with pytest.raises(StructureMismatchError):
        banded.solve_banded(synthetic.dense_matrix(32), b[:32])
    # a lied-about bandwidth is typed too
    with pytest.raises(StructureMismatchError):
        banded.solve_banded(a, b, bandwidth=1)


def test_blockdiag_one_dispatch_and_mismatch():
    from gauss_tpu.structure.blockdiag import _exe_cache

    a = synthetic.blockdiag_matrix(64 * 32, 32)  # the acceptance shape
    b = _rng(6).standard_normal(64 * 32)
    before = _exe_cache().misses + _exe_cache().hits
    x = blockdiag.solve_blockdiag(a, b)
    after = _exe_cache().misses + _exe_cache().hits
    assert after - before == 1  # 64 uniform blocks -> ONE vmap dispatch
    assert checks.residual_norm(a, x, b, relative=True) <= GATE
    with pytest.raises(StructureMismatchError):
        blockdiag.solve_blockdiag(synthetic.dense_matrix(32), b[:32])
    with pytest.raises(StructureMismatchError):
        # boundary that cuts through a block is a lie -> typed
        blockdiag.solve_blockdiag(a, b, blocks=(16,) + (32,) * 63 + (16,))


# ------------------------------------------------------------------ router

def test_solve_auto_bit_identical_to_direct_engines():
    rng = _rng(7)
    n = 48
    b = rng.standard_normal(n)

    a = synthetic.spd_matrix(n)
    res = solve_auto(a, b)
    assert res.rung == "cholesky" and not res.recovered
    direct, _ = cholesky.solve_spd_refined(a, b, panel=None, iters=2)
    np.testing.assert_array_equal(res.x, direct)

    a = synthetic.banded_matrix(n, 1)
    res = solve_auto(a, b)
    assert res.rung == "banded" and not res.recovered
    np.testing.assert_array_equal(
        res.x, banded.solve_banded_refined(a, b, iters=2))

    a = synthetic.blockdiag_matrix(n, 8)
    res = solve_auto(a, b)
    assert res.rung == "blockdiag" and not res.recovered
    np.testing.assert_array_equal(
        res.x, blockdiag.solve_blockdiag(a, b, refine_steps=2))

    from gauss_tpu.core import blocked

    a = synthetic.dense_matrix(n)
    res = solve_auto(a, b)
    assert res.rung == "blocked" and not res.recovered
    np.testing.assert_array_equal(
        res.x, blocked.solve_refined(a, b, iters=2)[0])


def test_solve_auto_trivial_and_errors():
    assert solve_auto(np.zeros((0, 0)), np.zeros(0)).x.shape == (0,)
    res = solve_auto(np.array([[4.0]]), np.array([2.0]))
    np.testing.assert_allclose(res.x, [0.5])
    with pytest.raises(ValueError):
        solve_auto(np.zeros((2, 3)), np.zeros(2))
    with pytest.raises(ValueError):
        solve_auto(np.eye(2), np.zeros(2), structure="wavelet")


def test_solve_auto_mistag_demotes_verified():
    """A forced wrong structure tag on every engine ends in a demotion to
    general LU with a verified solution or a typed error (the chaos
    structure phase runs the full matrix; this pins one pair per engine)."""
    from gauss_tpu.resilience import inject
    from gauss_tpu.structure.detect import STRUCTURE_KINDS

    rng = _rng(8)
    n = 48
    b = rng.standard_normal(n)
    # (true system, forced tag) chosen so the forced engine must FAIL
    cases = [
        (synthetic.dense_matrix(n), "spd"),        # not symmetric
        (synthetic.spd_matrix(n), "banded"),       # bandwidth too large
        (synthetic.banded_matrix(n, 1), "blockdiag"),  # one block only
    ]
    for a, wrong in cases:
        plan = inject.FaultPlan([inject.FaultSpec(
            site="structure.detect", kind="mistag",
            param=float(STRUCTURE_KINDS.index(wrong)), max_triggers=1)])
        with inject.plan(plan):
            res = solve_auto(a, b)
        assert res.recovered, (wrong, res.rung)
        assert checks.residual_norm(a, res.x, b, relative=True) <= GATE


def test_bucket_padding_preserves_structure():
    """Identity-extension bucket padding preserves SPD, bandwidth, and the
    block partition — the property that makes structure tags valid cache-
    key components in the serving layer."""
    from gauss_tpu.serve import buckets

    spd = synthetic.spd_matrix(24)
    ap, _ = buckets.pad_system(spd, np.zeros(24), 32)
    info = detect_structure(ap)
    assert info.spd_likely and info.symmetric

    tri = synthetic.banded_matrix(24, 1)
    ap, _ = buckets.pad_system(tri, np.zeros(24), 32)
    assert detect_structure(ap).bandwidth == 1

    bd = synthetic.blockdiag_matrix(24, 6)
    ap, _ = buckets.pad_system(bd, np.zeros(24), 32)
    assert detect_structure(ap).blocks[:4] == (6, 6, 6, 6)


# ----------------------------------------------------------------- serving

def test_serve_structure_aware_lanes():
    from gauss_tpu.serve import ServeConfig, SolverServer

    cfg = ServeConfig(ladder=(32, 64), max_batch=4, panel=16,
                      refine_steps=1, verify_gate=GATE,
                      structure_aware=True)
    rng = _rng(9)
    with SolverServer(cfg) as srv:
        handles = []
        for i in range(9):
            a = (synthetic.spd_matrix(24) if i % 3 == 0 else
                 synthetic.dense_matrix(24) if i % 3 == 1 else
                 synthetic.banded_matrix(40, 1))
            b = rng.standard_normal(a.shape[0])
            handles.append((a, b, srv.submit(a, b)))
        for a, b, h in handles:
            res = h.result(timeout=120)
            assert res.status == "ok", (res.status, res.error)
            assert checks.residual_norm(a, res.x, b, relative=True) <= GATE
        tags = {k.structure for k in srv.cache.keys()}
    assert "spd" in tags and "dense" in tags and "banded" in tags


def test_serve_structure_unaware_unchanged():
    from gauss_tpu.serve import ServeConfig, SolverServer
    from gauss_tpu.serve.cache import ExecutableCache

    cfg = ServeConfig(ladder=(32,), max_batch=2, panel=16,
                      verify_gate=GATE)
    # cache=: the all-keys-structure-None assertion below needs isolation
    # from the process-shared default cache other tests tag keys into.
    with SolverServer(cfg, cache=ExecutableCache(8)) as srv:
        res = srv.solve(synthetic.spd_matrix(16),
                        _rng(10).standard_normal(16))
        assert res.ok
        assert all(k.structure is None for k in srv.cache.keys())


def test_loadgen_structured_tokens():
    from gauss_tpu.serve import loadgen

    specs = loadgen.parse_mix("spd:24,banded:32/1,blockdiag:24/6*2")
    assert [s.kind for s, _ in specs] == ["spd", "banded", "blockdiag"]
    rng = _rng(11)
    for spec, _ in specs:
        a, b = loadgen.materialize(spec, rng)
        assert a.shape[0] == b.shape[0]
    assert structure_tag(loadgen.materialize(specs[0][0], rng)[0]) == "spd"
    with pytest.raises(ValueError):
        loadgen.parse_mix("spd:0")


# ------------------------------------------------- satellites: perf + gate

def test_checkpointed_path_none_is_fully_jitted_parity():
    """path=None compiles the one-program chunked factorization (no
    host-stepped group split) and is bit-identical to it."""
    import jax.numpy as jnp

    from gauss_tpu.core import blocked
    from gauss_tpu.resilience import checkpoint as ckpt

    rng = _rng(12)
    n = 64
    a = (rng.standard_normal((n, n)) + np.diag([float(n)] * n)).astype(
        np.float32)
    f1 = ckpt.lu_factor_blocked_chunked_checkpointed(a, None, panel=16,
                                                     chunk=2)
    f2 = blocked.lu_factor_blocked_chunked(jnp.asarray(a), panel=16,
                                           chunk=2)
    for fld in ("m", "perm", "min_abs_pivot", "linv", "uinv"):
        np.testing.assert_array_equal(np.asarray(getattr(f1, fld)),
                                      np.asarray(getattr(f2, fld)))


def test_regress_ratchet_gate():
    from gauss_tpu.obs import regress

    best = regress.RATCHET_BASELINES["gauss_n2048_wallclock"]
    ok = regress.evaluate_ratchet("gauss_n2048_wallclock", best * 1.2)
    assert ok["status"] == "ok"
    fast = regress.evaluate_ratchet("gauss_n2048_wallclock", best * 0.9)
    assert fast["status"] == "fast"
    bad = regress.evaluate_ratchet("gauss_n2048_wallclock",
                                   best * (regress.RATCHET_MAX_RATIO + 0.1))
    assert bad["status"] == "out-of-band"
    assert regress.evaluate_ratchet("no_such_metric", 1.0) is None


def test_structure_check_cli_smoke(tmp_path):
    from gauss_tpu.structure import check as scheck

    summary_path = tmp_path / "summary.json"
    rc = scheck.main(["--spd-n", "32", "--banded-n", "64", "--banded-bw",
                      "1", "--blockdiag-n", "32", "--block", "8",
                      "--dense-n", "32", "--repeats", "1",
                      "--summary-json", str(summary_path)])
    assert rc == 0
    import json

    summary = json.loads(summary_path.read_text())
    assert summary["kind"] == "structured_solve" and summary["ok"]
    assert set(summary["classes"]) == {"spd", "banded", "blockdiag",
                                       "dense"}
    assert summary["classes"]["spd"]["engine"] == "cholesky"
    # and the regress sentinel can ingest it
    from gauss_tpu.obs import regress

    recs = regress.ingest_file(summary_path)
    assert any(r["metric"] == "structure:spd/flops_ratio" for r in recs)


def test_chaos_structure_phase():
    from gauss_tpu.resilience.chaos import run_structure_phase

    out = run_structure_phase(seed=2584580, gate=GATE)
    assert out["violations"] == 0
    # 4 true structures x (len(STRUCTURE_KINDS) - 1) wrong tags; grew
    # from 12 when "sparse" joined the kind enumeration.
    assert out["injected"] == len(out["cases"]) == 16
    assert out["demotions"] >= 4  # every truly-wrong engine demoted


def test_summarize_structure_section(tmp_path):
    from gauss_tpu import obs
    from gauss_tpu.obs import registry, summarize

    out = tmp_path / "structure.jsonl"
    with obs.run(metrics_out=str(out)) as rec:
        solve_auto(synthetic.spd_matrix(24), np.ones(24))
    events = registry.read_events(str(out))
    st = summarize.structure_summary(events)
    assert st["detected"] == {"spd": 1}
    assert st["engines"] == {"cholesky": 1}
    assert st["demotions"] == 0
    text = summarize.summarize_run(events, rec.run_id)
    assert "structure lanes:" in text
    payload = summarize.run_summary(events, rec.run_id)
    assert payload["structure"]["solves"] == 1
