"""Profiling subsystem tests (SURVEY.md §5: phase timers + device traces)."""

import os

import jax.numpy as jnp

from gauss_tpu.utils import profiling


def test_phase_timer_accumulates_and_reports():
    pt = profiling.PhaseTimer()
    with pt.phase("init"):
        pass
    with pt.phase("computeGauss"):
        x = jnp.ones((8, 8)) @ jnp.ones((8, 8))
    with pt.phase("computeGauss", block_on=x):
        pass
    assert set(pt.seconds) == {"init", "computeGauss"}
    assert pt.total > 0
    rep = pt.report()
    assert "%time" in rep and "computeGauss" in rep
    # Percentages sum to ~100.
    pcts = [float(line.split()[0]) for line in rep.splitlines()[1:]]
    assert abs(sum(pcts) - 100.0) < 0.5


def test_trace_noop_without_dir():
    with profiling.trace(None):
        pass  # must not require jax.profiler at all


def test_trace_writes_profile(tmp_path):
    logdir = tmp_path / "trace"
    with profiling.trace(str(logdir)):
        jnp.ones((16, 16)).sum().block_until_ready()
    # jax.profiler.trace lays out plugins/profile/<run>/ with trace files.
    found = [os.path.join(r, f) for r, _, fs in os.walk(logdir) for f in fs]
    assert found, "trace produced no files"


def test_cli_profile_flag(capsys):
    from gauss_tpu.cli import gauss_internal

    rc = gauss_internal.main(["-s", "16", "--backend", "tpu-unblocked",
                              "--profile", "--verify"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Application time:" in out and "computeGauss" in out
