"""ABFT: checksum-carrying solves detect, localize, and repair SDC.

Covers the chaos campaign's new on-device ``sdc_bitflip`` phase end to
end: every injected corruption is detected by the checksum invariant,
localized to the offending panel group, and recovered via the localized
replay rung (bit-identical to an uninterrupted ABFT run) or ladder
escalation — plus the ABFT-off bit-identity / zero-overhead contract and
the GEMM single-element correction."""

import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from gauss_tpu.core import blocked
from gauss_tpu.io import synthetic
from gauss_tpu.resilience import abft, abftcheck, inject, recover
from gauss_tpu.structure import cholesky


def _dd_system(seed, n, dtype=np.float32):
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((n, n)).astype(dtype)
    a[np.arange(n), np.arange(n)] += np.asarray(n, dtype)
    return a, rng.standard_normal(n).astype(dtype)


def _assert_fields_equal(f0, f1, fields):
    for f in fields:
        assert np.array_equal(np.asarray(getattr(f0, f)),
                              np.asarray(getattr(f1, f))), f


LU_FIELDS = ("m", "perm", "min_abs_pivot", "linv", "uinv")
CHOL_FIELDS = ("m", "linv", "min_diag")


# -- checksum invariant + abft-off bit-identity ----------------------------

def test_flat_lu_abft_invariant_and_bit_identity():
    a, _ = _dd_system(0, 96)
    f0 = blocked.lu_factor_blocked(a, panel=16)
    f1 = blocked.lu_factor_blocked(a, panel=16, abft=True)
    assert f0.abft_err is None
    _assert_fields_equal(f0, f1, LU_FIELDS)
    errs = np.asarray(f1.abft_err)
    assert errs.shape == (7,)  # nb + final identity
    tol = abft.default_tol(96, np.float32, 96.0)
    assert float(errs.max()) < tol


def test_chunked_lu_abft_invariant_and_bit_identity():
    a, b = _dd_system(1, 96)
    f0 = blocked.lu_factor_blocked_chunked(a, panel=16, chunk=2)
    f1 = blocked.lu_factor_blocked_chunked(a, panel=16, chunk=2, abft=True)
    assert f0.abft_err is None
    _assert_fields_equal(f0, f1, LU_FIELDS)
    assert np.asarray(f1.abft_err).shape == (4,)  # 3 groups + final
    x = blocked.lu_solve(f1, jnp.asarray(b))
    rel = (np.linalg.norm(a @ np.asarray(x) - b)
           / np.linalg.norm(b))
    assert rel < 1e-4


def test_chol_flat_abft_invariant_and_bit_identity():
    a = synthetic.spd_matrix(96).astype(np.float32)
    f0 = cholesky.cholesky_factor_blocked(a, panel=16)
    f1 = cholesky.cholesky_factor_blocked(a, panel=16, abft=True)
    assert f0.abft_err is None
    _assert_fields_equal(f0, f1, CHOL_FIELDS)
    assert float(np.asarray(f1.abft_err).max()) < 1e-3


def test_chol_unrolled_rejects_abft():
    a = synthetic.spd_matrix(32).astype(np.float32)
    with pytest.raises(ValueError, match="flat fori form"):
        cholesky._factor_impl(a, 16, "highest", unrolled=True, abft=True)


def test_host_stepped_runners_match_jitted_forms():
    a, _ = _dd_system(2, 64)
    fac, rep = abft.lu_factor_abft(a, panel=16, chunk=2)
    ref = blocked.lu_factor_blocked_chunked(a, panel=16, chunk=2)
    _assert_fields_equal(fac, ref, LU_FIELDS)
    assert rep.detections == 0 and rep.replays == 0
    aspd = synthetic.spd_matrix(64).astype(np.float32)
    cfac, crep = abft.cholesky_factor_abft(aspd, panel=16)
    cref = cholesky.cholesky_factor_blocked(aspd, panel=16)
    _assert_fields_equal(cfac, cref, CHOL_FIELDS)
    assert crep.detections == 0


# -- the corruption primitive ----------------------------------------------

def test_flip_bit_roundtrip():
    a, _ = _dd_system(3, 16)
    m = jnp.asarray(a)
    m2 = abft.flip_bit(m, 3, 5, 30)
    assert not np.array_equal(np.asarray(m2), a)
    m3 = abft.flip_bit(m2, 3, 5, 30)
    assert np.array_equal(np.asarray(m3), a)  # XOR is its own inverse
    diff = np.argwhere(np.asarray(m2) != a)
    assert diff.tolist() == [[3, 5]]


def test_sdc_bitflip_kind_parses():
    plan = inject.FaultPlan.parse(
        "abft.lu.group=sdc_bitflip:skip=1:max=1")
    assert plan.specs[0].kind == "sdc_bitflip"
    assert plan.specs[0].site == "abft.lu.group"
    with pytest.raises(ValueError, match="unknown fault kind"):
        inject.FaultSpec(site="x", kind="sdc_flip")


# -- detect -> localize -> replay ------------------------------------------

def test_lu_detects_localizes_and_replays():
    a, b = _dd_system(4, 64)
    clean, _ = abft.lu_factor_abft(a, panel=16, chunk=1)
    plan = inject.FaultPlan([inject.FaultSpec(
        site=abft.SITE_LU, kind="sdc_bitflip", max_triggers=1, skip=2)],
        seed=7)
    with inject.plan(plan) as ap:
        fac, rep = abft.lu_factor_abft(a, panel=16, chunk=1)
    assert ap.stats()["triggered"] == 1
    assert rep.detections >= 1 and rep.replays >= 1
    assert not rep.escalated
    assert 2 in rep.detect_groups  # localized to the faulted group
    _assert_fields_equal(fac, clean, LU_FIELDS)  # bit-identical repair


def test_lu_last_group_fault_caught_by_final_identity():
    a, _ = _dd_system(5, 64)
    clean, _ = abft.lu_factor_abft(a, panel=16, chunk=1)
    plan = inject.FaultPlan([inject.FaultSpec(
        site=abft.SITE_LU, kind="sdc_bitflip", max_triggers=1, skip=3)],
        seed=5)
    with inject.plan(plan):
        fac, rep = abft.lu_factor_abft(a, panel=16, chunk=1)
    assert rep.detections >= 1 and not rep.escalated
    assert 3 in rep.detect_groups
    _assert_fields_equal(fac, clean, LU_FIELDS)


def test_lu_persistent_corruption_is_typed():
    a, _ = _dd_system(6, 64)
    plan = inject.FaultPlan([inject.FaultSpec(
        site=abft.SITE_LU, kind="sdc_bitflip", max_triggers=None,
        skip=1)], seed=3)
    with inject.plan(plan):
        with pytest.raises(abft.SDCUnrecoverableError) as ei:
            abft.lu_factor_abft(a, panel=16, chunk=1)
    assert ei.value.group == 1
    assert ei.value.magnitude > 0


def test_chol_detects_and_replays():
    a = synthetic.spd_matrix(64).astype(np.float32)
    clean, _ = abft.cholesky_factor_abft(a, panel=16)
    plan = inject.FaultPlan([inject.FaultSpec(
        site=abft.SITE_CHOL, kind="sdc_bitflip", max_triggers=1, skip=2)],
        seed=11)
    with inject.plan(plan):
        fac, rep = abft.cholesky_factor_abft(a, panel=16)
    assert rep.detections >= 1 and not rep.escalated
    _assert_fields_equal(fac, clean, CHOL_FIELDS)


def test_chol_not_spd_stays_typed_under_abft():
    # Symmetric but indefinite — the same input class the plain engine
    # rejects with its typed witness; the checksum machinery (computed
    # over the symmetrized-from-lower view the algorithm reads) must not
    # reclassify it as unrepairable SDC.
    rng = np.random.default_rng(7)
    a = rng.standard_normal((32, 32)).astype(np.float32)
    a = (a + a.T) / 2  # indefinite with overwhelming probability
    b = rng.standard_normal(32).astype(np.float32)
    with pytest.raises(cholesky.NotSPDError):
        abft.solve_chol_abft(a, b, panel=16)


# -- the ladder integration ------------------------------------------------

def test_ladders_gain_abft_heads():
    assert recover.default_rungs("blocked", abft=True)[0] == "abft"
    assert recover.default_rungs("blocked", abft=True)[1:] == \
        recover.default_rungs("blocked")
    assert recover.structured_rungs("spd", abft=True)[0] == "abft_chol"
    assert recover.structured_rungs("spd", abft=True)[1:] == \
        recover.structured_rungs("spd")
    # engines with no checksum form keep their ladder untouched
    assert recover.structured_rungs("banded", abft=True) == \
        recover.structured_rungs("banded")


def test_solve_resilient_replay_rung_and_sdc_tag():
    a, b = _dd_system(8, 128)
    a64, b64 = a.astype(np.float64), b.astype(np.float64)
    res0 = recover.solve_resilient(a64, b64, abft=True, panel=16)
    assert res0.rung == "abft" and not res0.sdc_detected
    assert res0.sdc is not None and res0.sdc["detections"] == 0
    plan = inject.FaultPlan([inject.FaultSpec(
        site=abft.SITE_LU, kind="sdc_bitflip", max_triggers=1, skip=1)],
        seed=4)
    with inject.plan(plan):
        res = recover.solve_resilient(a64, b64, abft=True, panel=16)
    assert res.rung == "abft" and res.rung_index == 0
    assert res.sdc_detected and res.sdc["replays"] >= 1
    # replay-recovered solve bit-identical to the uninterrupted one
    assert np.array_equal(res.x, res0.x)


def test_solve_resilient_escalates_past_failed_replay():
    a, b = _dd_system(9, 128)
    plan = inject.FaultPlan([inject.FaultSpec(
        site=abft.SITE_LU, kind="sdc_bitflip", max_triggers=None)],
        seed=4)
    with inject.plan(plan):
        res = recover.solve_resilient(a.astype(np.float64),
                                      b.astype(np.float64),
                                      abft=True, panel=16)
    assert res.rung_index > 0            # the full ladder served
    assert res.escalations[0][0] == "abft"
    assert res.sdc_detected              # the failed rung's report kept
    rel = (np.linalg.norm(a.astype(np.float64) @ res.x - b)
           / np.linalg.norm(b))
    assert rel < 1e-4


# -- abft matmul -----------------------------------------------------------

def test_abft_matmul_clean_and_corrected():
    rng = np.random.default_rng(10)
    a = rng.standard_normal((48, 32)).astype(np.float32)
    b = rng.standard_normal((32, 40)).astype(np.float32)
    c0, info0 = abft.abft_matmul(a, b)
    assert info0["detections"] == 0
    assert np.array_equal(np.asarray(c0),
                          np.asarray(abft.abft_matmul(a, b)[0]))
    plan = inject.FaultPlan([inject.FaultSpec(
        site=abft.SITE_MATMUL, kind="sdc_bitflip", max_triggers=1)],
        seed=9)
    with inject.plan(plan):
        c1, info = abft.abft_matmul(a, b)
    assert info["detections"] == 1
    assert info["corrected"] or info["recomputed"]
    dev = float(np.max(np.abs(np.asarray(c1) - np.asarray(c0))))
    assert dev <= info["tol"]


# -- obs + regress plumbing ------------------------------------------------

def test_sdc_summarize_section():
    from gauss_tpu import obs
    from gauss_tpu.obs import summarize

    a, b = _dd_system(11, 64)
    plan = inject.FaultPlan([inject.FaultSpec(
        site=abft.SITE_LU, kind="sdc_bitflip", max_triggers=1, skip=1)],
        seed=2)
    with obs.run(tool="test_sdc") as rec:
        with inject.plan(plan):
            abft.lu_factor_abft(a, panel=16, chunk=1)
    events = rec.events
    sd = summarize.sdc_summary(events)
    assert sd["detections"]["total"] >= 1
    assert sd["detections"]["by_engine"].get("lu", 0) >= 1
    assert sd["injected"]["total"] >= 1
    assert sd["max_magnitude"] > 0
    run_id = events[0]["run"]
    text = summarize.summarize_run(events, run_id)
    assert "sdc (abft checksum detections):" in text
    assert summarize.run_summary(events, run_id)["sdc"] == sd
    # the replay shows up as an abft_replay recovery in the resilience
    # section, the detection as a health gauge for the live plane
    rs = summarize.resilience_summary(events)
    assert rs["recoveries"]["by_rung"].get("abft_replay", 0) >= 1
    assert any(ev.get("type") == "health" and ev.get("sdc_detected")
               for ev in events)


def test_regress_ingests_abft_campaign(tmp_path):
    import json

    from gauss_tpu.obs import regress

    summary = {"kind": "abft_campaign",
               "sdc": {"cases": 10, "wall_s": 5.0, "escalated": 1,
                       "mean_detect_latency_s": 0.01},
               "identity": {"plain_s_per_solve": 0.001,
                            "overhead_ratio": 3.0}}
    p = tmp_path / "abft.json"
    p.write_text(json.dumps(summary))
    recs = regress.ingest_file(p)
    metrics = {r["metric"]: r["value"] for r in recs}
    assert metrics["abft:s_per_case"] == 0.5
    assert metrics["abft:plain_s_per_solve"] == 0.001
    assert metrics["abft:overhead_ratio"] == 3.0
    assert metrics["abft:escalation_rate"] == 0.1
    assert metrics["abft:detect_latency_s"] == 0.01


# -- serve + dist threading ------------------------------------------------

def test_serve_abft_tags_sdc_detected():
    from gauss_tpu.serve import ServeConfig, SolverServer

    a, b = _dd_system(12, 128)
    cfg = ServeConfig(ladder=(32, 64), panel=16, abft=True,
                      verify_gate=1e-4)
    plan = inject.FaultPlan([inject.FaultSpec(
        site=abft.SITE_LU, kind="sdc_bitflip", max_triggers=1, skip=1)],
        seed=2)
    with inject.plan(plan) as ap:
        with SolverServer(cfg) as srv:
            res = srv.solve(a, b, timeout=180)
    assert ap.stats()["triggered"] == 1
    assert res.ok and res.lane == "handoff"
    assert res.sdc_detected
    # abft off: field defaults False
    with SolverServer(ServeConfig(ladder=(32, 64), panel=16)) as srv:
        res2 = srv.solve(a, b, timeout=180)
    assert res2.ok and not res2.sdc_detected


def test_dist_blocked_abft_bit_identical():
    from gauss_tpu.dist import gauss_dist_blocked as gdb
    from gauss_tpu.dist.mesh import make_mesh

    a, b = _dd_system(13, 64, dtype=np.float64)
    mesh = make_mesh()
    x0 = gdb.gauss_solve_dist_blocked_refined(a, b, mesh=mesh, panel=8)
    x1 = gdb.gauss_solve_dist_blocked_refined(a, b, mesh=mesh, panel=8,
                                              abft=True)
    assert np.array_equal(x0, x1)
    rel = np.linalg.norm(a @ x1 - b) / np.linalg.norm(b)
    assert rel < 1e-9


# -- the campaign runner ---------------------------------------------------

def test_abftcheck_case_runner_invariant():
    cache = {}
    outcomes = [abftcheck.run_sdc_case(i, 99, 1e-4, clean_cache=cache)
                for i in range(8)]
    summ = abftcheck.summarize_sdc_cases(outcomes, 1.0)
    assert summ["missed"] == 0
    assert summ["violations"] == 0
    assert summ["detect_rate"] == 1.0
    replayed = [o for o in outcomes if o["outcome"] == "replayed"]
    assert replayed and all(o["bit_identical"] for o in replayed)
    assert all(o["localized"] for o in replayed)


@pytest.mark.slow
def test_abftcheck_cli_smoke(tmp_path):
    out = tmp_path / "summary.json"
    r = subprocess.run(
        [sys.executable, "-m", "gauss_tpu.resilience.abftcheck",
         "--cases", "12", "--seed", "77", "--matmul-cases", "2",
         "--summary-json", str(out)],
        capture_output=True, text=True, timeout=560)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "invariant HOLDS" in r.stdout
    import json

    summary = json.loads(out.read_text())
    assert summary["kind"] == "abft_campaign"
    assert summary["invariant_ok"]
    assert summary["identity"]["bit_identical"]
