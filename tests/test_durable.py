"""Durable-serving tests: the write-ahead request journal (CRC'd records,
torn-tail tolerance as a PROPERTY — every byte offset — batched fsync,
atomic rotation), crash -> restart recovery with exactly-once terminal
statuses, idempotency-key dedupe (journaled AND in-flight), graceful-drain
clean-shutdown markers, the supervisor loop, the reject-path trace
coverage, the kill-campaign case runner, and the regress/summarize ingest
for ``kind: durable_campaign``.

All CPU (conftest pins the platform); servers share one module-scoped
executable cache so the jitted batch executables compile once.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from gauss_tpu import obs
from gauss_tpu.obs import regress, requesttrace, summarize
from gauss_tpu.serve import (
    STATUS_EXPIRED,
    STATUS_OK,
    STATUS_REJECTED,
    ServeConfig,
    SolverServer,
    durable,
)
from gauss_tpu.serve.cache import ExecutableCache
from gauss_tpu.verify import checks

GATE = 1e-4


@pytest.fixture(scope="module")
def shared_cache():
    return ExecutableCache(64)


@pytest.fixture()
def rng():
    return np.random.default_rng(258458)


def _system(rng, n):
    a = rng.standard_normal((n, n))
    a[np.arange(n), np.arange(n)] += float(n)
    return a, rng.standard_normal(n)


def _config(journal_dir, **over):
    kw = dict(ladder=(16, 32), max_batch=4, panel=16, refine_steps=1,
              verify_gate=GATE, journal_dir=journal_dir,
              journal_fsync_batch=4)
    kw.update(over)
    return ServeConfig(**kw)


def _fill_journal(jd, records=10, terminals=6):
    jr = durable.RequestJournal(jd, fsync_batch=2, rotate_records=10_000)
    rng = np.random.default_rng(7)
    a, b = rng.standard_normal((4, 4)), rng.standard_normal(4)
    for i in range(records):
        jr.append_admit(id=i, request_id=f"r{i}", trace=f"t{i}", a=a, b=b,
                        was_vector=True, deadline_unix=None, dtype=None,
                        structure=None)
    for i in range(terminals):
        jr.append_terminal(id=i, request_id=f"r{i}", trace=f"t{i}",
                           status="ok", x=b, lane="batched",
                           rel_residual=1e-9)
    jr.close()
    return jr


# -- journal mechanics -----------------------------------------------------

def test_record_codec_roundtrip(rng):
    a = rng.standard_normal((5, 5))
    doc = {"rec": "admit", "id": 3, "a": durable.encode_array(a)}
    line = durable.encode_record(doc)
    back = durable.decode_line(line)
    assert back["id"] == 3
    assert np.array_equal(durable.decode_array(back["a"]), a)
    # any single corrupted byte in the body fails the CRC -> dropped
    corrupt = bytearray(line)
    corrupt[15] ^= 0x40
    assert durable.decode_line(bytes(corrupt)) is None


def test_torn_write_every_byte_offset_parses_longest_prefix(tmp_path):
    """The satellite property: truncating the segment at EVERY byte offset
    of the final record parses to the longest valid record prefix — a torn
    tail is dropped, never a crash, never a misparse."""
    jd = str(tmp_path / "j")
    _fill_journal(jd)
    path = durable.segment_paths(jd)[-1]
    data = open(path, "rb").read()
    last_start = data.rstrip(b"\n").rfind(b"\n") + 1
    total = durable.scan(jd).records
    for cut in range(last_start, len(data)):
        with open(path, "wb") as f:
            f.write(data[:cut])
        st = durable.scan(jd)
        # the record survives only once every body byte is present (the
        # trailing newline itself is not load-bearing)
        want = total if cut >= len(data) - 1 else total - 1
        assert st.records == want, (cut, st.records, want)
        assert st.torn_dropped == (0 if cut == last_start or want == total
                                   else 1)


def test_partial_line_merged_with_next_append_drops_both(tmp_path):
    """A torn record followed by a later append on the same line (no
    newline between them) fails the merged line's CRC: both are dropped,
    every record on its own line still parses."""
    jd = str(tmp_path / "j")
    _fill_journal(jd, records=4, terminals=2)
    path = durable.segment_paths(jd)[-1]
    data = open(path, "rb").read()
    last_start = data.rstrip(b"\n").rfind(b"\n") + 1
    extra = durable.encode_record({"rec": "terminal", "id": 3, "rid": "r3",
                                   "trace": "t3", "status": "failed",
                                   "schema": durable.JOURNAL_SCHEMA})
    with open(path, "wb") as f:
        f.write(data[:last_start + 8] + extra)  # torn tail + merged record
    st = durable.scan(jd)
    assert st.torn_dropped == 1
    assert "r3" not in st.by_rid          # the merged terminal is gone
    assert st.records == 6 - 1            # all fully-lined records survive


def test_rotation_compacts_and_carries_dedupe_window(tmp_path):
    jd = str(tmp_path / "j")
    jr = durable.RequestJournal(jd, fsync_batch=4, rotate_records=16)
    rng = np.random.default_rng(3)
    a, b = rng.standard_normal((4, 4)), rng.standard_normal(4)
    jr.append_admit(id=0, request_id="live0", trace="t", a=a, b=b,
                    was_vector=True, deadline_unix=None, dtype=None,
                    structure=None)
    for i in range(1, 30):
        jr.append_admit(id=i, request_id=f"k{i}", trace="t", a=a, b=b,
                        was_vector=True, deadline_unix=None, dtype=None,
                        structure=None)
        jr.append_terminal(id=i, request_id=f"k{i}", trace="t",
                           status="ok", x=b)
    assert jr.rotations >= 1
    jr.close()
    assert len(durable.segment_paths(jd)) <= 2  # old segments deleted
    st = durable.scan(jd)
    live = st.live_admits()
    assert [d["id"] for d in live] == [0]       # live admit carried
    assert "k29" in st.by_rid                   # dedupe window carried
    # rotation must not re-trigger per append once the carried set is big
    assert jr.rotations < 5


def test_clean_shutdown_marker_only_when_final(tmp_path):
    jd = str(tmp_path / "j")
    jr = durable.RequestJournal(jd)
    jr.append_shutdown()
    jr.close()
    assert durable.scan(jd).clean_shutdown
    jr2 = durable.RequestJournal(jd)
    rng = np.random.default_rng(1)
    jr2.append_admit(id=9, request_id=None, trace="t",
                     a=rng.standard_normal((3, 3)),
                     b=rng.standard_normal(3), was_vector=True,
                     deadline_unix=None, dtype=None, structure=None)
    jr2.close()
    st = durable.scan(jd)
    assert not st.clean_shutdown          # a later run reopened the journal
    assert len(st.live_admits()) == 1


# -- server integration ----------------------------------------------------

def test_journal_off_path_unchanged(rng, shared_cache):
    """journal_dir=None: no journal object, no terminal hook, and the
    client-visible result still carries its trace id (the loadgen-visible
    reject-tracing satellite applies to every status)."""
    with SolverServer(_config(None), cache=shared_cache) as srv:
        assert srv.journal is None
        a, b = _system(rng, 12)
        h = srv.submit(a, b)
        assert h._on_terminal is None
        res = h.result(30)
        assert res.status == STATUS_OK
        assert res.trace == h.trace_id


def test_crash_recovery_exactly_once_and_traces_complete(rng, shared_cache,
                                                         tmp_path):
    """Kill at a batch boundary -> restart -> every admitted request holds
    exactly one journaled terminal, served results verify at the gate from
    the JOURNALED operands, and the replayed terminals complete the
    ORIGINAL trace trees (requesttrace --check holds across the crash)."""
    jd = str(tmp_path / "j")
    stream = str(tmp_path / "events.jsonl")
    with obs.run(metrics_out=stream, tool="test_crash_recovery"):
        srv = SolverServer(_config(jd), cache=shared_cache).start()
        rids = []
        for j in range(4):                # served before the crash
            a, b = _system(rng, 20)
            srv.submit(a, b, request_id=f"c{j}", deadline_s=60.0)
            rids.append(f"c{j}")
        t0 = time.monotonic()             # let the worker terminal a few
        while (srv.requests_served < 2 and time.monotonic() - t0 < 30):
            time.sleep(0.005)
        srv._stop.set()                   # park the worker: the rest must
        srv._worker.join(timeout=30)      # still be QUEUED at crash time
        srv._worker = None
        for j in range(4, 8):
            a, b = _system(rng, 20)
            srv.submit(a, b, request_id=f"c{j}", deadline_s=60.0)
            rids.append(f"c{j}")
        srv._crash()
        st = durable.scan(jd)
        assert len(st.live_admits()) > 0  # the crash stranded real work
        srv2 = SolverServer(_config(jd), cache=shared_cache).start()
        assert srv2.last_resume["replayed"] == len(st.live_admits())
        srv2.stop(drain=True, timeout=120.0)
    st = durable.scan(jd)
    assert durable.scan(jd).clean_shutdown
    per_rid = {}
    for term in st.terminals.values():
        per_rid[term["rid"]] = per_rid.get(term["rid"], 0) + 1
    assert sorted(per_rid) == sorted(rids)
    assert all(v == 1 for v in per_rid.values())
    for doc in st.admits.values():
        term = st.terminals[doc["id"]]
        if term["status"] == "ok":
            a = durable.decode_array(doc["a"])
            b = durable.decode_array(doc["b"]).reshape(-1)
            x = durable.decode_array(term["x"])
            assert checks.residual_norm(a, x, b, relative=True) <= GATE
    from gauss_tpu.obs import registry

    trees = requesttrace.request_traces(registry.read_events(stream))
    assert len(trees) >= len(rids)
    assert requesttrace.check_traces(trees) == []


def test_clean_shutdown_replays_nothing(rng, shared_cache, tmp_path):
    jd = str(tmp_path / "j")
    srv = SolverServer(_config(jd), cache=shared_cache).start()
    a, b = _system(rng, 14)
    assert srv.solve(a, b, request_id="x0", timeout=60).status == STATUS_OK
    srv.stop(drain=True)
    srv2 = SolverServer(_config(jd), cache=shared_cache).start()
    assert srv2.last_resume == {"replayed": 0, "expired": 0, "clean": True,
                                "resume": True, "torn_dropped": 0}
    srv2.stop()


def test_duplicate_request_id_returns_journaled_status_without_resolving(
        rng, shared_cache, tmp_path):
    """The satellite property: a resubmission of a SERVED key returns the
    journaled status (solution included) without re-solving — across a
    server restart, and with zero new journal terminals."""
    jd = str(tmp_path / "j")
    a, b = _system(rng, 18)
    with SolverServer(_config(jd), cache=shared_cache) as srv:
        first = srv.solve(a, b, request_id="dup", timeout=60)
        assert first.status == STATUS_OK
    terms_before = len(durable.scan(jd).terminals)
    with SolverServer(_config(jd), cache=shared_cache) as srv2:
        again = srv2.solve(a, b, request_id="dup", timeout=5)
        assert again.status == STATUS_OK
        assert np.allclose(again.x, first.x)
        assert srv2.requests_served == 0          # zero duplicate solves
    assert len(durable.scan(jd).terminals) == terms_before


def test_pending_dedupe_attaches_to_inflight_request(rng, shared_cache,
                                                     tmp_path):
    """A resubmission while the key is still IN FLIGHT (queued or being
    replayed) attaches to the live request instead of admitting a
    duplicate — the hole the first campaign smoke found."""
    jd = str(tmp_path / "j")
    srv = SolverServer(_config(jd), cache=shared_cache)
    # not started: submissions queue, nothing resolves
    srv._closed = False
    a, b = _system(rng, 16)
    h1 = srv.submit(a, b, request_id="pend")
    h2 = srv.submit(a, b, request_id="pend")
    assert h2 is h1
    srv.start()
    assert h1.result(60).status == STATUS_OK
    srv.stop(drain=True)
    st = durable.scan(jd)
    assert sum(1 for t in st.terminals.values()
               if t.get("rid") == "pend") == 1


def test_expired_in_recovery_is_typed_terminal(rng, shared_cache, tmp_path):
    jd = str(tmp_path / "j")
    srv = SolverServer(_config(jd), cache=shared_cache)
    srv.start()
    a, b = _system(rng, 16)
    # submit with a deadline that will be dead by the (post-crash) restart
    # and crash before the worker can drain it: linger the worker first
    srv._stop.set()
    srv._worker.join(timeout=30)
    srv._worker = None
    h = srv.submit(a, b, request_id="late", deadline_s=0.05)
    assert not h.done
    srv._crash()
    time.sleep(0.1)
    srv2 = SolverServer(_config(jd), cache=shared_cache).start()
    assert srv2.last_resume["expired"] == 1
    srv2.stop(drain=True)
    st = durable.scan(jd)
    term = st.by_rid["late"]
    assert term["status"] == STATUS_EXPIRED
    assert "recovery" in term["error"]


def test_reject_terminals_carry_traces_loadgen_visible(rng, shared_cache,
                                                       tmp_path):
    """The reject-path tracing satellite: queue-full and server-stopped
    rejections carry the trace in BOTH the terminal event and the
    client-visible ServeResult, and requesttrace --check covers a stream
    of nothing but rejects."""
    stream = str(tmp_path / "rejects.jsonl")
    with obs.run(metrics_out=stream, tool="test_rejects"):
        cfg = _config(None, max_queue=0)
        srv = SolverServer(cfg, cache=shared_cache).start()
        a, b = _system(rng, 12)
        h = srv.submit(a, b)                      # queue_full reject
        res = h.result(5)
        assert res.status == STATUS_REJECTED
        assert res.trace == h.trace_id            # client-visible join key
        srv.stop()
        h2 = srv.submit(a, b)                     # server-stopped reject
        assert h2.result(5).status == STATUS_REJECTED
        assert h2.result(5).trace == h2.trace_id
    from gauss_tpu.obs import registry

    events = registry.read_events(stream)
    terminals = [ev for ev in events if ev.get("type") == "serve_request"]
    assert len(terminals) == 2
    assert all(ev.get("trace") for ev in terminals)
    trees = requesttrace.request_traces(events)
    assert requesttrace.check_traces(trees) == []


def test_heartbeat_written_from_worker_loop(rng, shared_cache, tmp_path):
    hb = str(tmp_path / "hb.json")
    with SolverServer(_config(None, heartbeat_path=hb),
                      cache=shared_cache) as srv:
        t0 = time.monotonic()
        while not os.path.exists(hb) and time.monotonic() - t0 < 10:
            time.sleep(0.02)
        assert os.path.exists(hb)
        doc = json.loads(open(hb).read())
        assert doc["pid"] == os.getpid()


def test_supervise_restarts_dead_child(tmp_path):
    """The watchdog loop itself, jax-free: a child that dies once (rc 113)
    then exits 0 must be restarted exactly once, and GAUSS_FAULTS must not
    leak into the respawn environment."""
    import sys as _sys

    marker = str(tmp_path / "died_once")
    hb = str(tmp_path / "hb.json")
    script = (
        "import os, sys, time\n"
        "open(os.environ['HB'], 'w').write('beat')\n"
        f"m = {marker!r}\n"
        "if not os.path.exists(m):\n"
        "    open(m, 'w').write('x')\n"
        "    assert os.environ.get('GAUSS_FAULTS') == 'armed'\n"
        "    os._exit(113)\n"
        "assert 'GAUSS_FAULTS' not in os.environ\n"
        "sys.exit(0)\n")
    env = dict(os.environ, HB=hb, GAUSS_FAULTS="armed")
    logs = []
    rc = durable.supervise([_sys.executable, "-c", script],
                           heartbeat_path=hb, max_restarts=2,
                           stall_after_s=60.0, env=env, log=logs.append)
    assert rc == 0
    assert any("restarting" in ln for ln in logs)


def test_inject_kinds_and_torn_write_hook():
    from gauss_tpu.resilience import inject

    plan = inject.FaultPlan.parse(
        "serve.server.batch=server_kill:skip=2;"
        "serve.journal.append=journal_torn_write:param=0.5")
    kinds = {sp.kind for sp in plan.specs}
    assert kinds == {"server_kill", "journal_torn_write"}
    with inject.plan(plan):
        # wrong-shape poll: server_kill site never fires the torn hook
        assert inject.poll_torn_write("serve.server.batch") is None
        sp = inject.poll_torn_write("serve.journal.append")
        assert sp is not None and sp.param == 0.5


def test_campaign_case_runner_each_kind(shared_cache, tmp_path):
    from gauss_tpu.serve import durablecheck

    for i, kind in enumerate(durablecheck.CASE_KINDS):
        out = durablecheck.run_recovery_case(i, 99, GATE, str(tmp_path),
                                             kind, cache=shared_cache)
        assert out["outcome"] == "ok", out
        assert out["audit"]["admitted"] >= 8
        assert out["deduped"] == out["audit"]["admitted"]
        assert out["dedupe_resolves"] == 0


def test_campaign_summary_regress_roundtrip(tmp_path):
    from gauss_tpu.serve.durablecheck import history_records

    summary = {"kind": "durable_campaign", "cases": 30, "wall_s": 45.0,
               "overhead": {"on": {"s_per_request": 0.0012},
                            "off": {"s_per_request": 0.0005},
                            "overhead_ratio": 2.4}}
    recs = history_records(summary)
    metrics = {m for m, _v, _u in recs}
    assert metrics == {"durable:s_per_case", "durable:journal_s_per_request"}
    path = tmp_path / "durable.json"
    path.write_text(json.dumps(summary))
    ingested = regress.ingest_file(path)
    assert {r["metric"] for r in ingested} == metrics
    assert all(r["kind"] == "durable" for r in ingested)


def test_summarize_durability_section(rng, shared_cache, tmp_path):
    jd = str(tmp_path / "j")
    stream = str(tmp_path / "durable_events.jsonl")
    with obs.run(metrics_out=stream, tool="test_durability_summary"):
        srv = SolverServer(_config(jd), cache=shared_cache).start()
        srv._stop.set()                   # park the worker: the submit
        srv._worker.join(timeout=30)      # below must still be queued
        srv._worker = None                # when the crash hits
        a, b = _system(rng, 14)
        srv.submit(a, b, request_id="s0")
        srv._crash()
        srv2 = SolverServer(_config(jd), cache=shared_cache).start()
        srv2.stop(drain=True, timeout=60)
        with SolverServer(_config(jd), cache=shared_cache) as srv3:
            srv3.solve(a, b, request_id="s0", timeout=10)
    from gauss_tpu.obs import registry

    events = registry.read_events(stream)
    run_id = events[0]["run"]
    doc = summarize.run_summary(events, run_id)
    du = doc["durability"]
    assert du["resumes"]["replayed"] == 1
    assert du["deduped"] == 1
    assert du["journal_events"]["open"] >= 3
    text = summarize.summarize_run(events, run_id)
    assert "durability (request journal):" in text


def test_loadgen_journal_report_and_request_ids(shared_cache, tmp_path):
    from gauss_tpu.serve.loadgen import LoadgenConfig, format_summary, \
        run_load

    cfg = LoadgenConfig(mix="random:14", requests=6, warmup=2,
                        concurrency=2, seed=5, request_ids=True,
                        serve=_config(str(tmp_path / "j")))
    with SolverServer(cfg.serve, cache=shared_cache) as srv:
        summary = run_load(srv, cfg)
    assert summary["counts"]["ok"] == 6
    assert summary["journal"]["appends"] > 0
    assert "journal:" in format_summary(summary)
    # the minted idempotency keys landed in the journal
    st = durable.scan(str(tmp_path / "j"))
    assert any(k.startswith("lg5-") for k in st.by_rid)


def test_stop_shutdown_race_still_exactly_one_terminal_with_journal(
        rng, shared_cache, tmp_path):
    """The PR-4 shutdown-race guarantee, now with the journal in the loop:
    every request that submit() admitted holds exactly one journaled
    terminal even when stop() races a burst of submitters."""
    jd = str(tmp_path / "j")
    srv = SolverServer(_config(jd), cache=shared_cache).start()
    a, b = _system(rng, 12)
    stop_now = threading.Event()
    admitted = []
    lock = threading.Lock()

    def submitter(k):
        for j in range(12):
            rid = f"race{k}-{j}"
            h = srv.submit(a, b, request_id=rid, deadline_s=30.0)
            if not (h.done and h.result(0).status == STATUS_REJECTED):
                with lock:
                    admitted.append(rid)
            if stop_now.is_set() and j > 4:
                return

    threads = [threading.Thread(target=submitter, args=(k,))
               for k in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.02)
    stop_now.set()
    srv.stop(drain=True, timeout=120.0)
    for t in threads:
        t.join()
    st = durable.scan(jd)
    per_rid = {}
    for term in st.terminals.values():
        if term.get("rid"):
            per_rid[term["rid"]] = per_rid.get(term["rid"], 0) + 1
    for rid in admitted:
        assert per_rid.get(rid, 0) == 1, rid
