"""Native C++ runtime tests: engines vs numpy, .dat parser parity, matrix_gen."""

import subprocess

import numpy as np
import pytest

from gauss_tpu import native
from gauss_tpu.io import datfile, synthetic

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


@pytest.mark.parametrize("engine", native.GAUSS_ENGINES)
def test_native_gauss_matches_numpy(rng, engine):
    n = 80
    a = rng.standard_normal((n, n))
    b = rng.standard_normal(n)
    x = native.gauss_solve(a, b, engine=engine, nthreads=4)
    np.testing.assert_allclose(x, np.linalg.solve(a, b), rtol=1e-9, atol=1e-9)


@pytest.mark.parametrize("engine", native.GAUSS_ENGINES)
def test_native_gauss_internal_pattern(engine):
    from gauss_tpu.verify import checks

    n = 128
    a = synthetic.internal_matrix(n)
    b = synthetic.internal_rhs(n)
    x = native.gauss_solve(a, b, engine=engine, nthreads=3)
    assert checks.internal_pattern_ok(x, atol=1e-8)


def test_native_singular_raises():
    a = np.ones((8, 8))
    b = np.ones(8)
    with pytest.raises(np.linalg.LinAlgError):
        native.gauss_solve(a, b, engine="seq")


@pytest.mark.parametrize("engine", native.MATMUL_ENGINES)
def test_native_matmul(rng, engine):
    n = 64
    a = rng.standard_normal((n, n))
    b = rng.standard_normal((n, n))
    c = native.matmul(a, b, engine=engine, nthreads=2)
    np.testing.assert_allclose(c, a @ b, rtol=1e-12)


def test_native_dat_parser_matches_python(tmp_path, rng):
    a = rng.standard_normal((17, 17))
    p = tmp_path / "m.dat"
    datfile.write_dat(p, a)
    via_native = native.read_dat_dense(str(p))
    via_python = datfile.read_dat_dense(p, engine="python")
    np.testing.assert_array_equal(via_native, via_python)
    np.testing.assert_array_equal(via_native, a)


def test_native_parser_rejects_bad_coords(tmp_path):
    p = tmp_path / "bad.dat"
    p.write_text("3 3 1\n0 3 5.0\n0 0 0\n")
    with pytest.raises(ValueError):
        native.read_dat_dense(str(p))


def test_native_parser_rejects_truncated(tmp_path):
    p = tmp_path / "trunc.dat"
    p.write_text("2 2 3\n1 1 1\n0 0 0\n")
    with pytest.raises(ValueError):
        native.read_dat_dense(str(p))


def test_matrix_gen_tool(tmp_path):
    """The C++ tool emits the generator matrix in valid .dat format."""
    out = subprocess.run([native.matrix_gen_path(), "5"],
                         capture_output=True, text=True, check=True)
    import io

    dense = datfile.read_dat_dense(io.StringIO(out.stdout), engine="python")
    np.testing.assert_array_equal(dense, synthetic.generator_matrix(5))
    lines = out.stdout.strip().split("\n")
    assert lines[0] == "5 5 25"
    assert lines[-1] == "0 0 0"


def test_matrix_gen_bad_args():
    rc = subprocess.run([native.matrix_gen_path()], capture_output=True)
    assert rc.returncode != 0
    rc = subprocess.run([native.matrix_gen_path(), "-3"], capture_output=True)
    assert rc.returncode != 0


@pytest.mark.parametrize("engine", ["forkjoin", "tiled"])
def test_new_engines_match_numpy(rng, engine):
    n = 70
    a = rng.standard_normal((n, n))
    b = rng.standard_normal(n)
    x = native.gauss_solve(a, b, engine=engine, nthreads=3)
    np.testing.assert_allclose(x, np.linalg.solve(a, b), rtol=1e-9, atol=1e-9)


def test_all_gauss_engines_agree(rng):
    """Every native engine produces the same solution bit-for-bit-close."""
    n = 60
    a = rng.standard_normal((n, n))
    b = rng.standard_normal(n)
    results = {e: native.gauss_solve(a, b, engine=e, nthreads=2)
               for e in native.GAUSS_ENGINES}
    ref = results["seq"]
    for e, x in results.items():
        np.testing.assert_allclose(x, ref, rtol=1e-12, atol=1e-12, err_msg=e)
