"""Serving-layer tests: bucket ladder + identity padding, the LRU
executable cache, admission control (queue bounds, deadlines), lane
degradation (retry -> NumPy fallback), multi-RHS end-to-end, the loadgen,
the summarizer's serving section, and the regress serve-ingest path.

All CPU (conftest pins the platform); the module-scoped server keeps the
jitted-executable compiles to one small set shared across tests.
"""

import json
import threading
import time

import numpy as np
import pytest

from gauss_tpu import obs
from gauss_tpu.core import blocked
from gauss_tpu.obs import regress, summarize
from gauss_tpu.serve import (
    STATUS_EXPIRED,
    STATUS_FAILED,
    STATUS_OK,
    STATUS_REJECTED,
    CacheKey,
    ExecutableCache,
    ServeConfig,
    ServeRequest,
    SolverServer,
    buckets,
)
from gauss_tpu.serve import loadgen
from gauss_tpu.verify import checks

LADDER = (16, 32)


def _system(rng, n, k=None):
    a = rng.standard_normal((n, n))
    a[np.arange(n), np.arange(n)] += float(n)  # diagonally dominant
    b = rng.standard_normal(n) if k is None else rng.standard_normal((n, k))
    return a, b


def _config(**over):
    kw = dict(ladder=LADDER, max_batch=4, panel=16, refine_steps=1,
              verify_gate=1e-4)
    kw.update(over)
    return ServeConfig(**kw)


@pytest.fixture(scope="module")
def server():
    with SolverServer(_config()) as srv:
        yield srv


# -- buckets ---------------------------------------------------------------

def test_bucket_ladder_and_pow2():
    assert buckets.bucket_for(1, LADDER) == 16
    assert buckets.bucket_for(16, LADDER) == 16
    assert buckets.bucket_for(17, LADDER) == 32
    assert buckets.bucket_for(33, LADDER) is None  # -> handoff lane
    with pytest.raises(ValueError):
        buckets.bucket_for(0, LADDER)
    assert [buckets.pow2_bucket(k) for k in (1, 2, 3, 5, 8)] == [1, 2, 4, 8, 8]
    assert buckets.pow2_bucket(9, cap=8) == 8
    # Default ladder rungs are panel multiples (no double padding).
    assert all(r % blocked.DEFAULT_PANEL == 0 for r in buckets.DEFAULT_LADDER)
    assert buckets.validate_ladder([64, 16, 64]) == (16, 64)
    with pytest.raises(ValueError):
        buckets.validate_ladder([])


def test_pad_system_identity_extension(rng):
    a, b = _system(rng, 5)
    ap, bp = buckets.pad_system(a, b, 8)
    assert ap.shape == (8, 8) and bp.shape == (8, 1)
    np.testing.assert_array_equal(ap[:5, :5], a)
    np.testing.assert_array_equal(ap[5:, 5:], np.eye(3))
    assert not ap[:5, 5:].any() and not ap[5:, :5].any()
    assert not bp[5:].any()
    # Multi-RHS with an RHS bucket wider than k.
    a, b = _system(rng, 5, k=2)
    _, bp = buckets.pad_system(a, b, 8, nrhs_bucket=4)
    assert bp.shape == (8, 4)
    np.testing.assert_array_equal(bp[:5, :2], b)
    assert not bp[:, 2:].any()
    with pytest.raises(ValueError):
        buckets.pad_system(a, b, 4)  # n exceeds bucket
    with pytest.raises(ValueError):
        buckets.pad_system(a, b[:4], 8)  # rhs rows mismatch


def test_padded_bucket_solve_bitmatches_unpadded(rng):
    """The acceptance-critical property: identity-extension padding changes
    NOTHING about the original system's solution — padded rows never win a
    pivot contest and every extra GEMM term multiplies zero, so the f32
    result at the original n is bit-identical, and the pad tail is exactly
    zero."""
    n = 20
    a, b = _system(rng, n)
    x = np.asarray(blocked.gauss_solve_blocked(
        a.astype(np.float32), b.astype(np.float32)))
    ap, bp = buckets.pad_system(a, b, 256)
    xp = np.asarray(blocked.gauss_solve_blocked(
        ap.astype(np.float32), bp.astype(np.float32)))
    np.testing.assert_array_equal(x[:n], xp[:n, 0])
    np.testing.assert_array_equal(xp[n:], np.zeros((256 - n, 1),
                                                   dtype=np.float32))


# -- executable cache ------------------------------------------------------

def _key(**over):
    kw = dict(bucket_n=16, nrhs=1, batch=1, dtype="float32",
              engine="blocked", refine_steps=1, mesh=None)
    kw.update(over)
    return CacheKey(**kw)


def test_lru_eviction_evicts_oldest():
    cache = ExecutableCache(capacity=2)
    built = []

    def builder(key):
        built.append(key)
        return object()

    k1, k2, k3 = _key(bucket_n=16), _key(bucket_n=32), _key(bucket_n=64)
    e1 = cache.get(k1, builder)
    cache.get(k2, builder)
    assert cache.get(k1, builder) is e1          # hit refreshes recency
    cache.get(k3, builder)                       # evicts k2 (oldest), not k1
    assert set(cache.keys()) == {k1, k3}
    assert cache.get(k1, builder) is e1          # k1 survived
    cache.get(k2, builder)                       # k2 must rebuild
    assert built == [k1, k2, k3, k2]
    s = cache.stats()
    assert s["evictions"] == 2 and s["hits"] == 2 and s["misses"] == 4
    with pytest.raises(ValueError):
        ExecutableCache(capacity=0)


# -- server: happy path ----------------------------------------------------

def test_server_batched_lane_correct_and_cached(server, rng):
    hits0 = server.cache.hits
    for n in (6, 12, 16, 24, 12, 6):
        a, b = _system(rng, n)
        res = server.solve(a, b)
        assert res.status == STATUS_OK and res.lane == "batched"
        assert res.bucket_n == buckets.bucket_for(n, LADDER)
        assert res.x.shape == (n,)
        x_ref = np.linalg.solve(a, b)
        assert checks.elementwise_match(res.x, x_ref, 1e-4)
        assert res.rel_residual <= 1e-4
    assert server.cache.hits > hits0  # repeated shapes reuse executables


def test_server_multirhs_shapes(server, rng):
    a, b = _system(rng, 12, k=3)
    res = server.solve(a, b)
    assert res.status == STATUS_OK
    assert res.x.shape == (12, 3)
    assert checks.residual_norm(a, res.x, b, relative=True) <= 1e-4
    # Vector in -> vector out, matrix in -> matrix out (shape-preserving).
    a1, b1 = _system(rng, 12)
    assert server.solve(a1, b1).x.shape == (12,)


def test_server_batches_queued_same_bucket(rng):
    """Requests queued while the worker is not yet running drain as ONE
    vmap batch (the dynamic-batching core), visible as a serve_batch event
    with occupancy > single."""
    srv = SolverServer(_config())
    handles = []
    with obs.run() as rec:
        for _ in range(3):
            a, b = _system(rng, 10)
            handles.append(srv.submit(a, b))
        srv.start()
        results = [h.result(120) for h in handles]
        srv.stop()
    assert all(r.status == STATUS_OK for r in results)
    batch_evs = [e for e in rec.events if e["type"] == "serve_batch"]
    assert any(e["batch"] == 3 and e["batch_bucket"] == 4 for e in batch_evs)
    occ = [e["occupancy"] for e in batch_evs if e["batch"] == 3]
    assert occ and occ[0] == pytest.approx(0.75)


def test_oversized_routes_through_handoff(server, rng):
    n = LADDER[-1] + 8
    a, b = _system(rng, n)
    with obs.run() as rec:
        res = server.solve(a, b)
    assert res.status == STATUS_OK and res.lane == "handoff"
    assert res.x.shape == (n,)
    assert checks.residual_norm(a, res.x, b, relative=True) <= 1e-4
    routes = [e for e in rec.events if e["type"] == "route"
              and e.get("tool") == "solve_handoff"]
    assert routes and routes[0]["lane"] == "single_chip"
    assert routes[0]["n"] == n and routes[0]["budget"] > 0


# -- admission control -----------------------------------------------------

def test_queue_full_rejection_with_retry_after(rng):
    srv = SolverServer(_config(max_queue=2))  # worker NOT started
    a, b = _system(rng, 8)
    h1, h2 = srv.submit(a, b), srv.submit(a, b)
    h3 = srv.submit(a, b)  # over the bound: rejected synchronously
    assert h3.done
    res3 = h3.result(0)
    assert res3.status == STATUS_REJECTED
    assert res3.retry_after_s and res3.retry_after_s > 0
    srv.stop(drain=False)  # refuses the queued two rather than losing them
    assert h1.result(5).status == STATUS_REJECTED
    assert h2.result(5).status == STATUS_REJECTED


def test_deadline_expired_rejected_before_compute(rng):
    srv = SolverServer(_config())
    a, b = _system(rng, 8)
    with obs.run() as rec:
        h = srv.submit(a, b, deadline_s=0.001)
        time.sleep(0.05)  # expire while queued (worker not started yet)
        live = srv.submit(a, b)  # no deadline — must still be served
        srv.start()
        res = h.result(120)
        assert live.result(120).status == STATUS_OK
        srv.stop()
    assert res.status == STATUS_EXPIRED and res.x is None
    evs = [e for e in rec.events if e["type"] == "serve_request"
           and e.get("status") == STATUS_EXPIRED]
    assert evs  # shed before compute, and visible in the stream
    # No batch was dispatched for the expired request alone.
    assert all(e.get("id") != h.id or e.get("status") == STATUS_EXPIRED
               for e in rec.events if e["type"] == "serve_request")


def test_default_deadline_applies(rng):
    srv = SolverServer(_config(deadline_default_s=0.001))
    a, b = _system(rng, 8)
    h = srv.submit(a, b)
    time.sleep(0.05)
    srv.start()
    assert h.result(120).status == STATUS_EXPIRED
    srv.stop()


def test_bad_request_shapes_raise(rng):
    a, b = _system(rng, 8)
    with pytest.raises(ValueError):
        ServeRequest(a[:, :4], b)
    with pytest.raises(ValueError):
        ServeRequest(a, b[:4])
    with pytest.raises(ValueError):
        ServeRequest(a, np.zeros((8, 2, 2)))


# -- degradation -----------------------------------------------------------

def test_numpy_fallback_lane_on_persistent_device_failure(rng):
    # cache=: these degradation tests patch cache.get; the default cache
    # is process-shared now, so the patch must stay private to this server.
    srv = SolverServer(_config(unhealthy_after=1, max_retries=1,
                               retry_backoff_s=0.0,
                               device_probe_cooldown_s=60.0),
                       cache=ExecutableCache(8))

    def broken_get(key, builder=None, panel=None):
        raise RuntimeError("injected transient device failure")

    srv.cache.get = broken_get
    a, b = _system(rng, 8)
    with obs.run() as rec:
        with srv:
            res = srv.solve(a, b)
            # Lane tripped: the next request goes straight to the host lane
            # (device_allowed() False) without touching the cache again.
            res2 = srv.solve(a, b)
    assert res.status == STATUS_OK and res.lane == "numpy"
    assert res2.status == STATUS_OK and res2.lane == "numpy"
    assert checks.residual_norm(a, res.x, b, relative=True) <= 1e-4
    assert srv.health.open
    retries = [e for e in rec.events if e["type"] == "serve_retry"]
    assert retries  # bounded retry ran before the lane tripped
    trips = [e for e in rec.events if e["type"] == "serve_fallback"]
    assert trips and trips[0]["lane"] == "numpy"


def test_nontransient_error_fails_without_retry(rng):
    srv = SolverServer(_config(), cache=ExecutableCache(8))  # patched below

    def broken_get(key, builder=None, panel=None):
        raise ValueError("deterministic bug — retrying replays it")

    srv.cache.get = broken_get
    a, b = _system(rng, 8)
    with obs.run() as rec:
        with srv:
            res = srv.solve(a, b)
    assert res.status == STATUS_FAILED
    assert "deterministic" in res.error
    assert not [e for e in rec.events if e["type"] == "serve_retry"]


def test_breaker_cooldown_probe_success_restores_device_lane(rng):
    """Full breaker lifecycle through the SERVER (not just LaneHealth):
    the device lane trips into numpy, the cooldown elapses, the probe batch
    goes back through the device lane, succeeds, and the lane is restored —
    the path test_serve.py never exercised before this PR."""
    # Private cache: the default is the PROCESS-SHARED instance now, and
    # this test monkeypatches cache.get — that must not leak into every
    # other server in the test process.
    srv = SolverServer(_config(unhealthy_after=1, max_retries=0,
                               retry_backoff_s=0.0,
                               device_probe_cooldown_s=0.15),
                       cache=ExecutableCache(8))
    real_get = srv.cache.get
    broken = {"on": True}

    def flaky_get(key, builder=None, panel=None):
        if broken["on"]:
            raise RuntimeError("injected transient device failure")
        return real_get(key, builder=builder, panel=panel)

    srv.cache.get = flaky_get
    a, b = _system(rng, 8)
    with srv:
        assert srv.solve(a, b).lane == "numpy"   # trips the breaker
        assert srv.health.open
        assert srv.solve(a, b).lane == "numpy"   # held open: no device try
        broken["on"] = False                      # device "recovers"
        time.sleep(0.2)                           # cooldown elapses
        res = srv.solve(a, b)                     # the probe batch
        assert res.status == STATUS_OK and res.lane == "batched"
        assert not srv.health.open                # circuit closed again
        assert srv.solve(a, b).lane == "batched"


def test_breaker_probe_failure_extends_cooldown(rng):
    """The other probe outcome: the probe batch fails, the breaker re-opens
    for another full cooldown, and requests stay on the numpy lane."""
    srv = SolverServer(_config(unhealthy_after=1, max_retries=0,
                               retry_backoff_s=0.0,
                               device_probe_cooldown_s=0.15),
                       cache=ExecutableCache(8))  # patched below: isolate
    probes = []

    def broken_get(key, builder=None, panel=None):
        probes.append(time.perf_counter())
        raise RuntimeError("injected transient device failure")

    srv.cache.get = broken_get
    a, b = _system(rng, 8)
    with obs.run() as rec:
        with srv:
            assert srv.solve(a, b).lane == "numpy"  # trips (1st device try)
            time.sleep(0.2)                          # cooldown elapses
            assert srv.solve(a, b).lane == "numpy"  # probe fails -> numpy
            assert srv.health.open                   # re-opened
            assert srv.solve(a, b).lane == "numpy"  # still held: NO probe
    assert len(probes) == 2  # initial failure + exactly one failed probe
    trips = [e for e in rec.events if e["type"] == "serve_fallback"]
    assert len(trips) == 2  # each failed probe re-trips with a fresh cooldown


def test_result_timeout_cancels_request(rng):
    """Satellite: a result(timeout) that expires CANCELS the queued request
    — the worker skips it (never serves into the void), and exactly one
    terminal 'cancelled' status/event exists (the result-timeout mirror of
    the stop()-race guarantee)."""
    from gauss_tpu.resilience import inject
    from gauss_tpu.serve import STATUS_CANCELLED

    a, b = _system(rng, 8)
    # Stall the worker before dispatch so the queued request is still
    # pending when the client gives up.
    plan = inject.FaultPlan([inject.FaultSpec(
        site="serve.worker.dispatch", kind="delay", param=0.4,
        max_triggers=None)])
    with obs.run() as rec:
        with inject.plan(plan):
            with SolverServer(_config()) as srv:
                h = srv.submit(a, b)
                with pytest.raises(TimeoutError, match="cancelled"):
                    h.result(timeout=0.05)
                assert h.done
                res = h.result(0)
                assert res.status == STATUS_CANCELLED
                # give the worker time to drain past the cancelled entry
                ok = srv.submit(a, b).result(timeout=60)
                assert ok.status == STATUS_OK
    # the cancelled request was resolved exactly once, and never served
    assert h.result(0).status == STATUS_CANCELLED
    terminal = [e for e in rec.events if e["type"] == "serve_request"
                and e.get("id") == h.id]
    assert len(terminal) == 1 and terminal[0]["status"] == STATUS_CANCELLED


def test_cancel_loses_race_to_completion(rng, server):
    """cancel() after the worker resolved is a no-op: the ok result stands
    and result(timeout) returns it instead of raising."""
    a, b = _system(rng, 8)
    h = server.submit(a, b)
    res = h.result(timeout=60)
    assert res.status == STATUS_OK
    assert h.cancel() is False
    assert h.result(0.001).status == STATUS_OK


def test_resolve_is_first_wins(rng):
    from gauss_tpu.serve import ServeResult
    from gauss_tpu.serve.admission import STATUS_CANCELLED

    req = ServeRequest(np.eye(4), np.ones(4))
    assert req.resolve(ServeResult(status=STATUS_OK)) is True
    assert req.resolve(ServeResult(status=STATUS_FAILED)) is False
    assert req.cancel() is False
    assert req.result(0).status == STATUS_OK
    req2 = ServeRequest(np.eye(4), np.ones(4))
    assert req2.cancel() is True
    assert req2.result(0).status == STATUS_CANCELLED


def test_supervised_handoff_lane(rng):
    """Oversized single-RHS requests route through the fleet supervisor
    when supervised_handoff is set: the route event says lane=fleet and
    the solution verifies."""
    a, b = _system(rng, 24)   # past the (16,) ladder top -> handoff lane
    cfg = _config(ladder=(16,), supervised_handoff=True, fleet_workers=1)
    with obs.run() as rec:
        with SolverServer(cfg) as srv:
            res = srv.solve(a, b, timeout=180)
    assert res.status == STATUS_OK and res.lane == "fleet"
    assert checks.residual_norm(a, res.x, b, relative=True) <= 1e-4
    routes = [e for e in rec.events if e["type"] == "route"
              and e.get("lane") == "fleet"]
    assert routes and routes[0]["tool"] == "serve_handoff"
    assert [e for e in rec.events if e["type"] == "fleet"
            and e.get("event") == "done"]


def test_stop_shutdown_race_every_request_terminal(rng):
    """The shutdown race the stop() rework pins: submits racing stop(drain)
    must each resolve with exactly one terminal status — served, rejected,
    or failed — never silently dropped."""
    srv = SolverServer(_config())
    srv.start()
    a, b = _system(rng, 8)
    handles = []
    stop_started = threading.Event()

    def submitter():
        for _ in range(200):
            handles.append(srv.submit(a, b))
            if stop_started.is_set():
                break

    threads = [threading.Thread(target=submitter) for _ in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.02)
    stop_started.set()
    srv.stop(drain=True, timeout=120)
    for t in threads:
        t.join()
    assert handles
    statuses = [h.result(timeout=30).status for h in handles]
    assert all(s in (STATUS_OK, STATUS_REJECTED, STATUS_FAILED)
               for s in statuses)
    # Post-stop submits reject synchronously instead of hanging a client.
    late = srv.submit(a, b)
    assert late.done and late.result(0).status == STATUS_REJECTED
    assert "stopped" in late.result(0).error


def test_lane_health_circuit_breaker():
    from gauss_tpu.serve.admission import LaneHealth

    h = LaneHealth(unhealthy_after=2, cooldown_s=30.0)
    assert h.device_allowed()
    assert not h.record_failure()     # 1 of 2: not yet tripped
    assert h.device_allowed()
    assert h.record_failure()         # trips
    assert not h.device_allowed() and h.open
    h2 = LaneHealth(unhealthy_after=1, cooldown_s=0.0)
    h2.record_failure()
    assert h2.device_allowed()        # cooldown elapsed: one probe allowed
    h2.record_success()
    assert not h2.open and h2.device_allowed()


# -- loadgen ---------------------------------------------------------------

def test_parse_mix_and_history_records():
    mix = loadgen.parse_mix("random:24*2, internal:16, dataset:jpwh_991")
    kinds = [(s.kind, s.arg) for s, _ in mix]
    assert kinds == [("random", "24"), ("internal", "16"),
                     ("dataset", "jpwh_991")]
    assert [w for _, w in mix] == [2.0, 1.0, 1.0]
    for bad in ("", "foo:12", "random", "random:0"):
        with pytest.raises(ValueError):
            loadgen.parse_mix(bad)
    recs = loadgen.history_records(
        {"mode": "closed", "throughput_rps": 20.0,
         "latency_s": {"p50": 0.01, "p95": 0.05, "p99": None}})
    assert ("serve:closed/s_per_request", 0.05) in recs
    assert ("serve:closed/p95_s", 0.05) in recs
    assert not any(m.endswith("p99_s") for m, _ in recs)


def test_loadgen_closed_loop_end_to_end(server, tmp_path):
    cfg = loadgen.LoadgenConfig(
        mix="random:10*2,random:20,internal:12", requests=8, warmup=2,
        concurrency=2, seed=7, serve=_config())
    with obs.run(metrics_out=str(tmp_path / "serve.jsonl")) as rec:
        summary = loadgen.run_load(server, cfg)
    assert summary["counts"]["ok"] == 8 and summary["incorrect"] == 0
    assert summary["throughput_rps"] > 0
    assert summary["latency_s"]["p50"] > 0
    assert summary["latency_s"]["p95"] >= summary["latency_s"]["p50"]
    assert summary["cache"]["hits"] + summary["cache"]["misses"] > 0
    assert "serve loadgen" in loadgen.format_summary(summary)
    # The summary is regress-ingestable end to end.
    out = tmp_path / "summary.json"
    loadgen.write_summary(summary, out)
    recs = regress.ingest_file(out)
    assert recs and all(r["kind"] == "serve" for r in recs)
    assert any(r["metric"] == "serve:closed/s_per_request" for r in recs)
    # And the loadgen's own events landed in the stream.
    assert [e for e in rec.events if e["type"] == "serve_loadgen"]


def test_loadgen_open_loop_poisson(server):
    cfg = loadgen.LoadgenConfig(mix="random:10", requests=4, warmup=0,
                                mode="open", rate=200.0, seed=3,
                                serve=_config())
    with obs.run():
        summary = loadgen.run_load(server, cfg)
    assert summary["counts"]["ok"] == 4 and summary["incorrect"] == 0
    with pytest.raises(ValueError):
        loadgen.run_load(server, loadgen.LoadgenConfig(
            mix="random:4", requests=1, warmup=0, mode="bogus"))


# -- summarizer serving section -------------------------------------------

def test_serving_summary_section_and_json(tmp_path):
    with obs.run(metrics_out=str(tmp_path / "sv.jsonl")) as rec:
        for i, lat in enumerate((0.01, 0.02, 0.03)):
            obs.emit("serve_request", id=i, n=16, status="ok",
                     lane="batched", latency_s=lat)
        obs.emit("serve_request", id=9, n=16, status="rejected",
                 reason="queue_full")
        obs.emit("serve_batch", bucket_n=16, batch=3, batch_bucket=4,
                 occupancy=0.75, seconds=0.01)
        obs.emit("serve_cache", event="miss", bucket_n=16)
        obs.emit("serve_cache", event="hit", bucket_n=16)
        obs.emit("serve_cache", event="hit", bucket_n=16)
        obs.emit("serve_retry", attempt=0, error="boom")
        obs.emit("route", tool="solve_handoff", n=40, lane="single_chip",
                 est_bytes=1, budget=2)
    events = obs.read_events(tmp_path / "sv.jsonl")
    sv = summarize.serving_summary(events)
    assert sv["requests"] == {"ok": 3, "rejected": 1}
    assert sv["lanes"] == {"batched": 3}
    assert sv["latency_s"]["p50"] == pytest.approx(0.02)
    assert sv["batches"] == {"count": 1, "occupancy_mean": 0.75}
    assert sv["cache"]["hit"] == 2 and sv["cache"]["miss"] == 1
    assert sv["cache"]["hit_rate"] == pytest.approx(2 / 3)
    assert sv["retries"] == 1
    assert sv["handoff_routes"] == {"single_chip": 1}
    text = summarize.summarize_events(events, rec.run_id)
    assert "serving:" in text and "hit-rate" in text
    payload = summarize.run_summary(events, rec.run_id)
    json.dumps(payload)  # --json path stays serializable
    assert payload["serving"]["requests"]["ok"] == 3
    # Runs with no serving events carry an empty section, not noise.
    with obs.run(metrics_out=str(tmp_path / "plain.jsonl")) as r2:
        obs.emit("custom")
    plain = obs.read_events(tmp_path / "plain.jsonl")
    assert summarize.serving_summary(plain) == {}
    assert "serving:" not in summarize.summarize_events(plain, r2.run_id)


# -- regress serve history -------------------------------------------------

def test_regress_serve_history_roundtrip(tmp_path):
    summary = {"kind": "serve_loadgen", "mode": "closed",
               "throughput_rps": 25.0,
               "latency_s": {"p50": 0.008, "p95": 0.02}}
    art = tmp_path / "serve_summary.json"
    art.write_text(json.dumps(summary))
    recs = regress.ingest_file(art)
    assert {r["metric"] for r in recs} == {
        "serve:closed/s_per_request", "serve:closed/p50_s",
        "serve:closed/p95_s"}
    hist = tmp_path / "history.jsonl"
    assert regress.append_history(recs, hist) == 3
    assert regress.append_history(recs, hist) == 0  # idempotent re-ingest
    # Below min-samples the verdict is informational, never a gate failure.
    verdicts = regress.check_records(recs, regress.load_history(hist))
    assert all(v["status"] == "no-baseline" for v in verdicts)
    # With three epochs the baseline gates: a 2x p95 is out of band.
    for v in (0.019, 0.021):
        regress.append_history([dict(recs[2], value=v, source=f"e{v}")], hist)
    bad = regress.evaluate("serve:closed/p95_s", 0.06,
                           regress.load_history(hist))
    assert bad["status"] == "out-of-band"
    ok = regress.evaluate("serve:closed/p95_s", 0.021,
                          regress.load_history(hist))
    assert ok["status"] in ("ok", "fast")
