"""Double-single arithmetic tests (core.dsfloat; VERDICT round 1 #3).

The accuracy assertions here are deliberately tight (~1e-12 relative): they
are the regression guard for the formulation constraint documented in the
module — if a future refactor lets the elementwise products fuse back into
the compensated reduction, XLA:CPU silently degrades results to plain-f32
accuracy (~1e-8), and these tests catch it.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from gauss_tpu.core import dsfloat
from gauss_tpu.verify import checks


def _rep(ds):
    """The f64 value a DS pair represents."""
    return np.asarray(ds.hi, np.float64) + np.asarray(ds.lo, np.float64)


def test_to_ds_round_trip():
    rng = np.random.default_rng(0)
    a = rng.standard_normal(1000) * 1e3
    d = dsfloat.to_ds(a)
    # hi+lo carries the f64 value to ~2^-48 relative.
    assert np.max(np.abs(dsfloat.ds_to_f64(d) - a) / np.abs(a)) < 1e-13
    assert d.hi.dtype == jnp.float32 and d.lo.dtype == jnp.float32


def test_two_sum_two_prod_exact():
    """The error-free transformations must be exactly error-free in f32."""
    import jax

    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.standard_normal(4096), jnp.float32)
    b = jnp.asarray(rng.standard_normal(4096) * rng.uniform(1e-6, 1e6, 4096),
                    jnp.float32)
    s, e = jax.jit(dsfloat._two_sum)(a, b)
    exact = np.asarray(a, np.float64) + np.asarray(b, np.float64)
    assert np.array_equal(np.asarray(s, np.float64) + np.asarray(e, np.float64),
                          exact)
    p, e = jax.jit(dsfloat._two_prod)(a, b)
    exactp = np.asarray(a, np.float64) * np.asarray(b, np.float64)
    # p + e == a*b to ~2^-58 relative (the exact-partial-products TwoProd
    # leaves one tiny rounding on the e-channel combination).
    err = np.abs(np.asarray(p, np.float64) + np.asarray(e, np.float64) - exactp)
    assert np.max(err / np.maximum(np.abs(exactp), 1e-30)) < 2**-50


def test_two_prod_broadcast_operands_jit():
    """The corruption's original reproducer: a (n, m) x (n, 1) broadcast
    product under jit on CPU. Must hold the same exactness bar."""
    import jax

    rng = np.random.default_rng(123)
    a = jnp.asarray(rng.standard_normal((8, 8)), jnp.float32)
    x = jnp.asarray(rng.standard_normal(8), jnp.float32)
    p, e = jax.jit(lambda a, x: dsfloat._two_prod(a, x[:, None]))(a, x)
    exact = np.asarray(a, np.float64) * np.asarray(x, np.float64)[:, None]
    err = np.abs(np.asarray(p, np.float64) + np.asarray(e, np.float64) - exact)
    assert np.max(err / np.maximum(np.abs(exact), 1e-30)) < 2**-50


@pytest.mark.parametrize("n,m", [(8, 8), (33, 17), (256, 300), (1024, 1024)])
def test_ds_matvec_accuracy(n, m):
    """ds_matvec must be accurate to ~2^-47, NOT plain-f32 (~2^-24) — the
    regression bar for the fused-product corruption (module docstring)."""
    rng = np.random.default_rng(n * 1000 + m)
    A = rng.standard_normal((m, n))
    x = rng.standard_normal(n)
    at = dsfloat.to_ds(A.T)
    xd = dsfloat.to_ds(x)
    truth = (_rep(at).T) @ _rep(xd)
    got = dsfloat.ds_to_f64(dsfloat.ds_matvec(at, xd))
    scale = np.max(np.abs(A) @ np.abs(x))  # accumulation magnitude
    assert np.max(np.abs(got - truth)) / scale < n * 1e-13


def test_ds_residual_captures_cancellation():
    """b - A x with x near the true solution: the residual is ~1e-7 of b's
    magnitude, and double-single resolves it to several digits — plain f32
    would return pure noise."""
    rng = np.random.default_rng(7)
    n = 200
    A = rng.standard_normal((n, n)) + n * np.eye(n)
    x_true = rng.standard_normal(n)
    b = A @ x_true
    x = x_true * (1 + 1e-7)  # a perturbed "solution"
    r_true = b - A @ x
    at = dsfloat.to_ds(A.T)
    r = dsfloat.ds_to_f64(
        dsfloat.ds_residual(at, dsfloat.to_ds(x), dsfloat.to_ds(b)))
    denom = np.max(np.abs(r_true))
    assert np.max(np.abs(r - r_true)) / denom < 1e-4


def test_solve_ds_well_conditioned():
    rng = np.random.default_rng(3)
    n = 192
    A = rng.standard_normal((n, n)) + n * np.eye(n)
    x_true = rng.standard_normal(n)
    b = A @ x_true
    x, fac = dsfloat.solve_ds(A, b, iters=3)
    assert checks.max_rel_error(x, x_true) < 1e-9
    assert float(fac.min_abs_pivot) > 0


def test_solve_ds_ill_conditioned_beats_f32_refinement():
    """A graded ill-conditioned system (cond ~1e6): plain-f32 refinement
    stalls above the 1e-4 bar, double-single sails under it — the exact
    failure mode of the round-1 memplus/saylr4 device cells."""
    import jax

    from gauss_tpu.core import blocked

    rng = np.random.default_rng(4)
    n = 256
    # Graded singular values 1 .. 1e-6.
    u, _ = np.linalg.qr(rng.standard_normal((n, n)))
    v, _ = np.linalg.qr(rng.standard_normal((n, n)))
    s = np.logspace(0, -6, n)
    A = (u * s) @ v.T
    x_true = rng.standard_normal(n)
    b = A @ x_true

    # Plain f32 on-device refinement (the old configuration).
    fac = blocked.lu_factor_blocked(jnp.asarray(A, jnp.float32), panel=64)
    x32 = blocked.lu_solve(fac, jnp.asarray(b, jnp.float32))
    for _ in range(6):
        r = jnp.asarray(b, jnp.float32) - jnp.asarray(A, jnp.float32) @ x32
        x32 = x32 + blocked.lu_solve(fac, r)
    err32 = checks.max_rel_error(np.asarray(x32, np.float64), x_true)

    x, _ = dsfloat.solve_ds(A, b, iters=6, panel=64)
    errds = checks.max_rel_error(x, x_true)
    assert errds < 1e-4, errds
    assert errds < err32 / 10, (errds, err32)


@pytest.mark.slow
def test_solve_ds_real_saylr4():
    """The real worst case: saylr4 read in place from the reference checkout
    (skips when absent)."""
    from gauss_tpu.io import reference_data

    if not reference_data.available():
        pytest.skip("no reference checkout")
    a = reference_data.load_dense("saylr4")
    n = a.shape[0]
    x_true = np.arange(1, n + 1, dtype=np.float64)
    b = a @ x_true
    x, _ = dsfloat.solve_ds(a, b, iters=6)
    assert checks.max_rel_error(x, x_true) < 1e-4
