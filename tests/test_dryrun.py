"""Regression tests for the driver's multi-chip dryrun (VERDICT round 1 #1).

Round-1 failure mode: the driver's independent 8-device dryrun crashed with a
libtpu client/terminal version mismatch because ``jnp.asarray`` in the dist
engines staged operands through the *default* backend (the tunneled TPU) even
though the mesh was CPU-only. The fix stages all dist operands host-side and
``device_put``s them directly onto the mesh's devices, and the dryrun pins the
default device to the fallback platform.

The poisoned test emulates a present-but-broken non-CPU default backend by
monkeypatching jax's batched_device_put to raise whenever staging targets a
non-CPU device — the exact failure shape of MULTICHIP_r01.json — and asserts
the dryrun still completes on the virtual CPU mesh.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_POISON_SCRIPT = r"""
import sys
sys.path.insert(0, %(repo)r)

import jax  # noqa: F401  (initialize config before poisoning)
from jax._src.interpreters import pxla

_orig = pxla.batched_device_put
_calls = [0]


def _poisoned(aval, sharding, xs, devices, *a, **k):
    _calls[0] += 1
    bad = [d for d in devices if getattr(d, "platform", "cpu") != "cpu"]
    if bad:
        raise RuntimeError(
            "poisoned: staging to non-cpu default backend %%r" %% (bad[:1],))
    return _orig(aval, sharding, xs, devices, *a, **k)


pxla.batched_device_put = _poisoned

import __graft_entry__

__graft_entry__.dryrun_multichip(8)
# Prove the hook is live on the staging path (on CPU-only hosts the poison
# cannot fire, but staging must still have flowed through it).
assert _calls[0] > 0, "poison hook never saw a device_put"
print("POISON-DRYRUN-OK")
"""


@pytest.mark.slow
def test_dryrun_survives_poisoned_default_backend():
    """dryrun_multichip(8) must succeed even when every non-CPU device_put
    raises — i.e. a broken default TPU client cannot poison a CPU-mesh run."""
    env = dict(os.environ)
    # Mimic the driver environment: do NOT pin the platform; whatever default
    # the image's sitecustomize selects (possibly a tunneled TPU) must be
    # irrelevant to the outcome.
    env.pop("JAX_PLATFORMS", None)
    env.pop("GAUSS_TPU_TEST_PLATFORM", None)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _POISON_SCRIPT % {"repo": REPO}],
            capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    except subprocess.TimeoutExpired:
        # A HANGING default backend (tunneled-TPU outage: even backend init
        # blocks forever) is an environment condition no in-process defense
        # can absorb — distinct from the broken-but-responsive backend this
        # test covers, and distinct from a genuine dryrun deadlock. Tell
        # them apart before skipping: a trivial op on the default backend
        # must ALSO hang for the outage explanation to hold (observed
        # round 4 during a >1 h tunnel outage).
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax, jax.numpy as jnp; print(jnp.ones(2).sum())"],
                capture_output=True, text=True, timeout=60, env=env, cwd=REPO)
            probe_hung = probe.returncode != 0
        except subprocess.TimeoutExpired:
            probe_hung = True
        if not probe_hung:
            raise AssertionError(
                "dryrun subprocess timed out while the default backend "
                "answers a trivial op — a genuine hang in the dryrun path")
        pytest.skip("default backend init hung (device tunnel outage) — "
                    "environmental, not a dryrun defect")
    assert proc.returncode == 0, (
        f"dryrun died under poisoned default backend:\n{proc.stderr[-4000:]}")
    assert "POISON-DRYRUN-OK" in proc.stdout


def test_dist_operands_committed_to_mesh_devices():
    """_prepare must return arrays committed to the mesh's devices with the
    row-sharded NamedSharding — never uncommitted default-device arrays."""
    import jax
    import numpy as np

    from gauss_tpu.dist import make_mesh
    from gauss_tpu.dist.gauss_dist import _prepare
    from gauss_tpu.io import synthetic

    mesh = make_mesh(4)
    n = 12
    a = synthetic.internal_matrix(n, dtype=np.float32)
    b = synthetic.internal_rhs(n, dtype=np.float32)
    a_c, b_c, npad = _prepare(a, b, mesh)
    assert npad % 4 == 0
    P = jax.sharding.PartitionSpec
    for arr, spec in ((a_c, P("rows", None)), (b_c, P("rows"))):
        sh = arr.sharding
        assert isinstance(sh, jax.sharding.NamedSharding)
        assert sh.mesh.devices.tolist() == mesh.devices.tolist()
        assert sh.spec == spec
        assert arr.committed


def test_prepare_2d_committed_to_mesh_devices():
    import jax
    import numpy as np

    from gauss_tpu.dist.gauss_dist2d import _prepare_2d
    from gauss_tpu.dist.mesh import make_mesh_2d
    from gauss_tpu.io import synthetic

    mesh = make_mesh_2d(2, 2)
    n = 10
    a = synthetic.internal_matrix(n, dtype=np.float32)
    b = synthetic.internal_rhs(n, dtype=np.float32)
    a_c, b_c, npad, cperm = _prepare_2d(a, b, mesh)
    assert a_c.committed and b_c.committed
    assert a_c.sharding.mesh.devices.tolist() == mesh.devices.tolist()
