"""Tests for the 2-D panel-blocked distributed factorization (VERDICT r2 #4).

Covers: oracle agreement on 4x2 and 2x4 virtual meshes (incl. systems that
REQUIRE pivoting), padding and dtype paths, singular detection, the
factored re-solve path, refinement, and the collective-count/traffic
claims — counted from the compiled jaxpr, not asserted from prose.
"""

import numpy as np
import pytest

import jax

from gauss_tpu.dist import gauss_dist_blocked as gdb
from gauss_tpu.dist import gauss_dist_blocked2d as g2d
from gauss_tpu.dist.mesh import make_mesh, make_mesh_2d
from gauss_tpu.verify import checks

from tests.test_dist_blocked import _count_collectives


@pytest.fixture(scope="module")
def mesh42():
    return make_mesh_2d(4, 2)


@pytest.fixture(scope="module")
def mesh24():
    return make_mesh_2d(2, 4)


def _system(n, rng, dominant=True):
    a = rng.standard_normal((n, n))
    if dominant:
        a = a + n * np.eye(n)
    x_true = rng.standard_normal(n)
    return a, a @ x_true, x_true


@pytest.mark.parametrize("n,panel", [(32, 4), (64, 8), (100, 8), (192, 16)])
def test_matches_truth_4x2(mesh42, rng, n, panel):
    a, b, x_true = _system(n, rng)
    x = np.asarray(g2d.gauss_solve_dist_blocked2d(a, b, mesh=mesh42,
                                                  panel=panel))
    assert checks.max_rel_error(x, x_true) < 1e-9


@pytest.mark.parametrize("n,panel", [(64, 8), (100, 8)])
def test_matches_truth_2x4(mesh24, rng, n, panel):
    a, b, x_true = _system(n, rng)
    x = np.asarray(g2d.gauss_solve_dist_blocked2d(a, b, mesh=mesh24,
                                                  panel=panel))
    assert checks.max_rel_error(x, x_true) < 1e-9


def test_pivoting_required(mesh42, rng):
    """Zero diagonal: unsolvable without pivoting; the tournament must
    elect valid off-diagonal pivots and the routed swaps must agree."""
    n = 64
    a = rng.standard_normal((n, n))
    np.fill_diagonal(a, 0.0)
    x_true = rng.standard_normal(n)
    b = a @ x_true
    assert np.isfinite(np.linalg.cond(a))
    x = np.asarray(g2d.gauss_solve_dist_blocked2d(a, b, mesh=mesh42,
                                                  panel=8))
    assert checks.max_rel_error(x, x_true) < 1e-8


def test_duplicate_rows_across_shards(mesh42):
    """Round-3 regression: the reference's synthetic internal matrix has
    whole runs of IDENTICAL rows within a panel's columns, so most shards'
    local candidate blocks are rank-deficient. The unguarded election
    NaN-poisoned the argmax and dropped rank-carrying rows (solution came
    back inf); the zero-pivot-safe election must solve it exactly."""
    from gauss_tpu.io import synthetic

    n = 64
    a = synthetic.internal_matrix(n, dtype=np.float32)
    b = synthetic.internal_rhs(n, dtype=np.float32)
    x = np.asarray(g2d.gauss_solve_dist_blocked2d(a, b, mesh=mesh42,
                                                  panel=4), np.float64)
    ref = np.linalg.solve(a.astype(np.float64), b.astype(np.float64))
    assert np.isfinite(x).all()
    np.testing.assert_allclose(x, ref, rtol=1e-3, atol=1e-3)


def test_agrees_with_1d_blocked(mesh42, rng):
    """The 2-D engine and the 1-D blocked engine solve the same system to
    the same answer (both f64; different pivot orders, same solution)."""
    a, b, x_true = _system(96, rng)
    x2 = np.asarray(g2d.gauss_solve_dist_blocked2d(a, b, mesh=mesh42,
                                                   panel=8))
    x1 = np.asarray(gdb.gauss_solve_dist_blocked(a, b, mesh=make_mesh(8),
                                                 panel=8))
    assert checks.max_rel_error(x2, x_true) < 1e-9
    assert checks.elementwise_match(x2, x1, epsilon=1e-8)


def test_float32_path(mesh42, rng):
    a, b, x_true = _system(64, rng)
    x = np.asarray(g2d.gauss_solve_dist_blocked2d(
        a.astype(np.float32), b.astype(np.float32), mesh=mesh42, panel=8))
    assert checks.max_rel_error(x, x_true) < 1e-3


def test_refined_reaches_f64(mesh42, rng):
    n = 96
    a, b, x_true = _system(n, rng)
    x = g2d.gauss_solve_dist_blocked2d_refined(a, b, mesh=mesh42, panel=8,
                                               iters=3)
    assert x.dtype == np.float64
    assert checks.max_rel_error(x, x_true) < 1e-9


def test_factored_resolve_new_rhs(mesh42, rng):
    n = 96
    a, b, _ = _system(n, rng)
    staged = g2d.prepare_dist_blocked2d(a, b, mesh42, panel=8)
    fac = g2d.factor_dist_blocked2d(staged, mesh42)
    x2_true = rng.standard_normal(n)
    x2 = np.asarray(g2d.lu_solve_dist_blocked2d(fac, a @ x2_true))
    assert checks.max_rel_error(x2, x2_true) < 1e-9


def test_singular_detected(mesh42):
    n = 32
    a = np.ones((n, n))  # rank 1
    staged = g2d.prepare_dist_blocked2d(a, np.ones(n), mesh42, panel=8)
    fac = g2d.factor_dist_blocked2d(staged, mesh42)
    assert float(fac.min_piv) == 0.0


def test_recommend_engine_routing_rule(mesh42, rng):
    """The measured 1-D/2-D crossover is an API, not a table to eyeball
    (VERDICT r3 weak #6): below n=1024 the 1-D blocked engine, at or above
    it the 2-D tournament engine — and the recommended engine solves."""
    import gauss_tpu.dist as dist

    assert dist.recommend_engine(512) is gdb.gauss_solve_dist_blocked_refined
    assert (dist.recommend_engine(1024)
            is g2d.gauss_solve_dist_blocked2d_refined)
    assert (dist.recommend_engine(2048, ndev=8)
            is g2d.gauss_solve_dist_blocked2d_refined)
    a, b, x_true = _system(64, rng)
    x = dist.recommend_engine(64)(a, b, mesh=make_mesh(4))
    assert checks.max_rel_error(np.asarray(x), x_true) < 1e-9


def test_singular_raises_on_solve_entries(mesh42):
    """ADVICE r3: the convenience and refined entries must not return an
    authoritative-looking answer from a rank-deficient factorization — the
    zero tournament pivot is the witness and both entries raise on it."""
    n = 32
    a = np.ones((n, n))  # rank 1
    b = np.ones(n)
    with pytest.raises(np.linalg.LinAlgError, match="singular"):
        g2d.gauss_solve_dist_blocked2d(a, b, mesh=mesh42, panel=8)
    with pytest.raises(np.linalg.LinAlgError, match="singular"):
        g2d.gauss_solve_dist_blocked2d_refined(a, b, mesh=mesh42, panel=8)


def test_nonsingular_min_piv_positive(mesh42, rng):
    a, b, _ = _system(64, rng)
    staged = g2d.prepare_dist_blocked2d(a, b, mesh42, panel=8)
    fac = g2d.factor_dist_blocked2d(staged, mesh42)
    assert float(fac.min_piv) > 0.0


def test_auto_panel_dist2d():
    # Small systems shrink the panel so padding stays bounded.
    assert g2d.auto_panel_dist2d(64, 4, 2) == 16
    assert g2d.auto_panel_dist2d(4096, 4, 2) == 128
    # lcm matters: a (4, 3) grid pads to multiples of 12 * panel.
    assert g2d.auto_panel_dist2d(128, 4, 3) == 8


def test_block_cyclic_perm_2d_roundtrip():
    perm = g2d._block_cyclic_perm_2d(64, 4, 8)
    assert sorted(perm.tolist()) == list(range(64))
    # Shard 0's first block is global block 0; shard 1's is global block 1.
    assert perm[0] == 0 and perm[16] == 8


def test_collective_count_o_n_over_panel(mesh42):
    """THE design claim: 3 collectives per panel in the factorization,
    independent of n within a panel — counted from the traced jaxpr."""
    n, panel = 128, 8
    a = np.eye(n, dtype=np.float32)
    staged = g2d.prepare_dist_blocked2d(a, np.zeros(n, np.float32), mesh42,
                                        panel=panel)
    fac_fn = g2d._build_factor_2d(mesh42, staged[3], panel,
                                  str(staged[0].dtype))
    jaxpr = jax.make_jaxpr(fac_fn)(staged[0])
    count = _count_collectives(jaxpr.jaxpr)
    nblocks = staged[3] // panel
    # Exactly 3 per panel (strip psum + tournament gather + routing psum)
    # + the closing pmin pairs (4 replicated outputs x 2 axes).
    assert count <= 3 * nblocks + 8, (count, nblocks)


def test_strip_traffic_scales_down_with_mesh_rows(mesh42):
    """The 2-D engine's reason to exist: no collective in the factorization
    carries an operand proportional to the FULL matrix rows (npad); the
    biggest gathered/summed operand is O(npad/R * panel + R * panel^2) per
    panel, versus the 1-D engine's O(npad * panel) strip all_gather. Checked
    from the jaxpr by bounding every collective operand's size."""
    n, panel = 128, 8
    R = mesh42.devices.shape[0]
    a = np.eye(n, dtype=np.float32)

    staged = g2d.prepare_dist_blocked2d(a, np.zeros(n, np.float32), mesh42,
                                        panel=panel)
    npad = staged[3]
    fac_fn = g2d._build_factor_2d(mesh42, npad, panel, str(staged[0].dtype))
    jaxpr = jax.make_jaxpr(fac_fn)(staged[0])

    def max_collective_operand(jaxpr):
        biggest = 0
        for eqn in jaxpr.eqns:
            if any(c in eqn.primitive.name for c in
                   ("psum", "all_gather", "ppermute", "all_to_all")):
                for v in eqn.invars:
                    size = 1
                    for s in getattr(v.aval, "shape", ()):
                        size *= s
                    biggest = max(biggest, size)
            for v in eqn.params.values():
                if hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
                    biggest = max(biggest, max_collective_operand(v.jaxpr))
                elif hasattr(v, "eqns"):
                    biggest = max(biggest, max_collective_operand(v))
        return biggest

    biggest = max_collective_operand(jaxpr.jaxpr)
    # Routing psum: (panel, 2*mc + 2*panel); strip psum: (mr, panel);
    # tournament gather: (panel, panel) -> (R*panel, panel) result. All are
    # far below a full (npad, panel) strip once the mesh grows.
    mr = npad // R
    mc = npad // mesh42.devices.shape[1]
    bound = max(panel * (2 * mc + 2 * panel), mr * panel, R * panel * panel)
    assert biggest <= bound, (biggest, bound)
    # And the 1-D engine's defining operand WOULD be npad * panel.
    assert bound < npad * panel * R  # sanity: the bound is meaningful


def test_rectangular_mesh_padding(mesh24, rng):
    """n not a multiple of panel * lcm(R, C): identity padding must keep
    the solution exact on the real block."""
    n = 50
    a, b, x_true = _system(n, rng)
    x = np.asarray(g2d.gauss_solve_dist_blocked2d(a, b, mesh=mesh24,
                                                  panel=8))
    assert checks.max_rel_error(x, x_true) < 1e-9


def test_1d_mesh_rejected(rng):
    with pytest.raises(ValueError, match="2-D mesh"):
        g2d.gauss_solve_dist_blocked2d(np.eye(8), np.ones(8),
                                       mesh=make_mesh(4))
