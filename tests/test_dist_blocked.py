"""Tests for the panel-blocked distributed factorization (VERDICT r1 #4).

Covers: oracle agreement on the 8-virtual-device mesh (incl. systems that
REQUIRE pivoting), padding and dtype paths, singular detection, and the
collective-count reduction proof — counted from the compiled jaxpr (scan
lengths are static), not asserted from prose.
"""

import numpy as np
import pytest

import jax

from gauss_tpu.dist import gauss_dist, gauss_dist_blocked as gdb
from gauss_tpu.dist.mesh import make_mesh
from gauss_tpu.verify import checks


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def _system(n, rng, dominant=True):
    a = rng.standard_normal((n, n))
    if dominant:
        a = a + n * np.eye(n)
    x_true = rng.standard_normal(n)
    return a, a @ x_true, x_true


@pytest.mark.parametrize("n,panel", [(24, 4), (64, 8), (100, 8), (192, 16)])
def test_matches_truth(mesh, rng, n, panel):
    a, b, x_true = _system(n, rng)
    x = np.asarray(gdb.gauss_solve_dist_blocked(a, b, mesh=mesh, panel=panel))
    assert checks.max_rel_error(x, x_true) < 1e-10


def test_pivoting_required(mesh, rng):
    """Zero diagonal entries: without partial pivoting this system is
    unsolvable; the replicated panel factorization must pick the same
    off-diagonal pivots on every shard."""
    n = 48
    a = rng.standard_normal((n, n))
    np.fill_diagonal(a, 0.0)
    x_true = rng.standard_normal(n)
    b = a @ x_true
    assert np.isfinite(np.linalg.cond(a))
    x = np.asarray(gdb.gauss_solve_dist_blocked(a, b, mesh=mesh, panel=8))
    assert checks.max_rel_error(x, x_true) < 1e-9


def test_agrees_with_per_step_engine(mesh, rng):
    """Blocked and per-step distributed engines solve the same system to the
    same answer (both f64 here)."""
    a, b, x_true = _system(72, rng)
    xb = np.asarray(gdb.gauss_solve_dist_blocked(a, b, mesh=mesh, panel=8))
    xs = np.asarray(gauss_dist.gauss_solve_dist(a, b, mesh=mesh))
    assert checks.elementwise_match(xb, xs, epsilon=1e-9)
    assert checks.max_rel_error(xb, x_true) < 1e-10


def test_float32_path(mesh, rng):
    a, b, x_true = _system(64, rng)
    x = np.asarray(gdb.gauss_solve_dist_blocked(
        a.astype(np.float32), b.astype(np.float32), mesh=mesh, panel=8))
    assert checks.max_rel_error(x, x_true) < 1e-3


def test_factored_resolve_new_rhs(mesh, rng):
    """One distributed factorization serves further O(n^2) solves: the
    factored-solve path must agree with a from-scratch solve on a fresh
    right-hand side (the getrf/getrs split, distributed)."""
    n = 96
    a, b, _ = _system(n, rng)
    staged = gdb.prepare_dist_blocked(a, b, mesh, panel=8)
    x1, fac = gdb.factor_solve_dist_blocked_staged(staged, mesh)
    # A second RHS through the SAME factors.
    x2_true = rng.standard_normal(n)
    b2 = a @ x2_true
    x2 = np.asarray(gdb.lu_solve_dist_blocked(fac, b2))
    assert checks.max_rel_error(x2, x2_true) < 1e-9
    # And the factor-time solution itself round-trips.
    x1_again = np.asarray(gdb.lu_solve_dist_blocked(fac, b))
    assert checks.elementwise_match(np.asarray(x1), x1_again, epsilon=1e-9)


def test_factored_resolve_pivoting_required(mesh, rng):
    """The composed permutation returned by the factorization must be the
    real P of PA = LU: solving a new RHS on a zero-diagonal system exercises
    it (an identity perm would scramble the substitution)."""
    n = 48
    a = rng.standard_normal((n, n))
    np.fill_diagonal(a, 0.0)
    x_true = rng.standard_normal(n)
    staged = gdb.prepare_dist_blocked(a, a @ x_true, mesh, panel=8)
    _, fac = gdb.factor_solve_dist_blocked_staged(staged, mesh)
    x2_true = rng.standard_normal(n)
    x2 = np.asarray(gdb.lu_solve_dist_blocked(fac, a @ x2_true))
    assert checks.max_rel_error(x2, x2_true) < 1e-8


def test_refined_beats_raw_f32(mesh, rng):
    """gauss_solve_dist_blocked_refined in f32 must reach accuracy raw f32
    cannot (the ADVICE round-2 contract for solve_handoff's far route)."""
    n = 96
    a, b, x_true = _system(n, rng)
    x_raw = np.asarray(gdb.gauss_solve_dist_blocked(
        a.astype(np.float32), b.astype(np.float32), mesh=mesh, panel=8))
    x_ref = gdb.gauss_solve_dist_blocked_refined(a, b, mesh=mesh, panel=8,
                                                 iters=3)
    assert x_ref.dtype == np.float64
    err_raw = checks.max_rel_error(x_raw, x_true)
    err_ref = checks.max_rel_error(x_ref, x_true)
    assert err_ref < 1e-9
    assert err_ref < err_raw / 10


def test_singular_detected(mesh):
    """A singular matrix must produce a zero min-pivot (not a crash/hang)."""
    n = 32
    a = np.ones((n, n))  # rank 1
    b = np.ones(n)
    staged = gdb.prepare_dist_blocked(a, b, mesh, panel=8)
    solver = gdb._build_solver_blocked(mesh, staged[2], staged[3],
                                       str(staged[0].dtype))
    *_, min_piv = solver(staged[0])
    assert float(min_piv) == 0.0


def test_block_cyclic_perm_roundtrip():
    perm = gdb._block_cyclic_perm(64, 8, 4)
    assert sorted(perm.tolist()) == list(range(64))
    # shard 0's first block is global block 0; shard 1's is global block 1.
    assert perm[0] == 0 and perm[8] == 4  # m = 8 rows/shard, panel = 4


COLLECTIVE_NAMES = ("psum", "all_gather", "ppermute", "all_to_all", "pmin",
                    "pmax")


def _count_collectives(jaxpr, mult=1):
    """Total collective ops per execution, weighting scan bodies by their
    static lengths (fori_loop with static bounds lowers to scan).

    Nested jaxprs are found by duck-typing (a ClosedJaxpr has .jaxpr, a
    Jaxpr has .eqns) rather than isinstance against jax internals, which
    survives JAX's private-module refactors (ADVICE round 2)."""
    total = 0
    for eqn in jaxpr.eqns:
        if any(c in eqn.primitive.name for c in COLLECTIVE_NAMES):
            total += mult
        inner_mult = mult * eqn.params.get("length", 1)
        for v in eqn.params.values():
            if hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
                total += _count_collectives(v.jaxpr, inner_mult)
            elif hasattr(v, "eqns"):
                total += _count_collectives(v, inner_mult)
    return total


def test_collective_count_reduction(mesh):
    """THE design claim: collectives per panel, not per row. Counted from
    the traced jaxprs of both engines on the same padded size."""
    n, panel = 256, 32
    a = np.eye(n, dtype=np.float32)
    b = np.zeros(n, dtype=np.float32)

    staged_b = gdb.prepare_dist_blocked(a, b, mesh, panel=panel)
    solver_b = gdb._build_solver_blocked(mesh, staged_b[2], staged_b[3],
                                         str(staged_b[0].dtype))
    jaxpr_b = jax.make_jaxpr(solver_b)(staged_b[0])
    count_b = _count_collectives(jaxpr_b.jaxpr)

    staged_s = gauss_dist.prepare_dist(a, b, mesh)
    solver_s = gauss_dist._build_solver(mesh, staged_s[3],
                                        str(staged_s[0].dtype))
    jaxpr_s = jax.make_jaxpr(solver_s)(staged_s[0], staged_s[1])
    count_s = _count_collectives(jaxpr_s.jaxpr)

    nblocks = staged_b[2] // panel
    # Blocked: ~3 per panel (+1 closing pmin). Per-step: >= 3 per pivot row.
    assert count_b <= 4 * nblocks + 2, (count_b, nblocks)
    assert count_s >= 3 * staged_s[3], (count_s, staged_s[3])
    # The headline: at least a panel-width-order reduction.
    assert count_b * 8 <= count_s, (count_b, count_s)
