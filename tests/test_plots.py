"""Result-graph rendering tests (C11 analog; no device work needed)."""

import json

from gauss_tpu.bench import plots


def _cells():
    return [
        {"suite": "gauss-internal", "key": "1024", "backend": "tpu",
         "seconds": 0.03, "verified": True, "error": 0.0, "reference_s": 1.31},
        {"suite": "gauss-internal", "key": "2048", "backend": "tpu",
         "seconds": 0.045, "verified": True, "error": 0.0, "reference_s": 0.509},
        {"suite": "gauss-internal", "key": "2048", "backend": "seq",
         "seconds": 1.3, "verified": True, "error": 0.0, "reference_s": 10.98},
        {"suite": "matmul", "key": "1024", "backend": "tpu",
         "seconds": 0.08, "verified": True, "error": 0.0, "reference_s": 0.0897},
        {"suite": "matmul", "key": "2048", "backend": "tpu",
         "seconds": 0.09, "verified": True, "error": 0.0, "reference_s": 0.1149},
        # Unverified cells must never be plotted.
        {"suite": "matmul", "key": "4096", "backend": "tpu",
         "seconds": 0.0, "verified": False, "error": None, "reference_s": None},
    ]


def test_plots_render_all_three(tmp_path):
    src = tmp_path / "cells.json"
    src.write_text(json.dumps(_cells()))
    out = tmp_path / "graphs"
    rc = plots.main([str(src), "--outdir", str(out)])
    assert rc == 0
    names = {p.name for p in out.iterdir()}
    assert names == {"gauss_scaling.png", "gauss_engines.png",
                     "matmul_scaling.png"}
    assert all((out / n).stat().st_size > 5000 for n in names)


def test_plots_empty_input_fails(tmp_path, capsys):
    src = tmp_path / "cells.json"
    src.write_text("[]")
    rc = plots.main([str(src), "--outdir", str(tmp_path / "g")])
    assert rc == 1
    assert "no verified cells" in capsys.readouterr().err


def test_engine_identities_are_unique():
    # Color+linestyle follows the entity; no two engines share a pair, and
    # unknown engines fold to gray rather than colliding with a real one.
    pairs = [(plots._color(e), plots._linestyle(e)) for e in plots.ENGINE_STYLE]
    assert len(set(pairs)) == len(plots.ENGINE_STYLE)
    assert plots._color("mystery-engine") == plots.GRAY
