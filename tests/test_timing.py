"""Timing helper tests (utils/timing.py — the gettimeofday-span analog)."""

import numpy as np

from gauss_tpu.utils import timing


def test_timed_returns_best_and_result():
    calls = []

    def fn(x):
        calls.append(1)
        return np.asarray(x) * 2

    best, result = timing.timed(fn, 21, warmup=2, reps=3)
    assert result == 42
    assert best >= 0.0
    assert len(calls) == 5  # 2 warmups + 3 reps


def test_timed_fetch_fetches_tree():
    best, result = timing.timed_fetch(lambda: {"a": np.ones(3)}, warmup=0,
                                      reps=2)
    assert isinstance(result["a"], np.ndarray)
    assert best >= 0.0
