"""Timing helper tests (utils/timing.py — the gettimeofday-span analog)."""

import numpy as np

from gauss_tpu.utils import timing


def test_timed_returns_best_and_result():
    calls = []

    def fn(x):
        calls.append(1)
        return np.asarray(x) * 2

    best, result = timing.timed(fn, 21, warmup=2, reps=3)
    assert result == 42
    assert best >= 0.0
    assert len(calls) == 5  # 2 warmups + 3 reps


def test_timed_fetch_fetches_tree():
    best, result = timing.timed_fetch(lambda: {"a": np.ones(3)}, warmup=0,
                                      reps=2)
    assert isinstance(result["a"], np.ndarray)
    assert best >= 0.0


def test_fetch_staged_bounds_pytrees():
    """fetch_staged must touch one element of every leaf (the tunneled
    completion bound for staged uploads — see the memplus 86-267 s staging
    leak it fixes) and hand the arrays back unchanged, pytrees included."""
    import jax.numpy as jnp

    a = jnp.arange(6.0).reshape(2, 3)
    tree = {"hi": jnp.ones(4), "lo": jnp.zeros((2, 2))}
    scalar = jnp.asarray(7.0)
    out = timing.fetch_staged(a, tree, scalar)
    assert out[0] is a and out[1] is tree and out[2] is scalar
    np.testing.assert_array_equal(np.asarray(out[0]),
                                  np.arange(6.0).reshape(2, 3))


def test_force_host_device_count_flag_logic(monkeypatch):
    from gauss_tpu.utils import env

    monkeypatch.setenv("XLA_FLAGS", "")
    assert env.force_host_device_count(8) is True
    assert "--xla_force_host_platform_device_count=8" in \
        __import__("os").environ["XLA_FLAGS"]
    # existing larger request: fine; smaller: reported
    monkeypatch.setenv("XLA_FLAGS", "--xla_force_host_platform_device_count=16")
    assert env.force_host_device_count(8) is True
    monkeypatch.setenv("XLA_FLAGS", "--xla_force_host_platform_device_count=2")
    assert env.force_host_device_count(8) is False


def test_honor_jax_platforms(monkeypatch):
    """The shared sitecustomize workaround (examples + conftest): applies
    JAX_PLATFORMS through jax.config (which beats a later platform pin),
    no-ops when unset."""
    import jax

    from gauss_tpu.utils import env

    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    assert env.honor_jax_platforms() is False
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert env.honor_jax_platforms() is True
    assert jax.config.jax_platforms == "cpu"
