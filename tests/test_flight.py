"""Flight-recorder tests: the mmap ring codec (roundtrip, wrap, torn
tail, resync past damage, oversize drop), the third-sink install/restore
contract and sidecar tracking, post-mortem bundle capture / integrity
checking / throttled triggers, the unclean-resume capture a journaled
``start()`` performs BEFORE replaying, crash-spanning trace folding
(``fold_ring_events``), the /snapshot + Prometheus flight surfaces, and
the ``gauss-debug`` CLI.

All CPU (conftest pins the platform); the serving tests share one
module-scoped executable cache so the batch executables compile once.
"""

import json
import os
import time

import numpy as np
import pytest

from gauss_tpu import obs
from gauss_tpu.obs import debug as gdebug
from gauss_tpu.obs import export as gexport
from gauss_tpu.obs import flight, postmortem, requesttrace
from gauss_tpu.obs import spans as _spans
from gauss_tpu.serve import ServeConfig, SolverServer, durable
from gauss_tpu.serve.cache import ExecutableCache

GATE = 1e-4


@pytest.fixture(scope="module")
def shared_cache():
    return ExecutableCache(64)


@pytest.fixture(autouse=True)
def _no_leaked_flight_state():
    """Flight sink and trigger are process-global: every test leaves them
    exactly as it found them (None — the suite never runs flight-armed)."""
    yield
    flight.uninstall()
    postmortem.uninstall_trigger()
    assert _spans.flight_sink() is None


def _payloads(n, tag="ev"):
    return [json.dumps({"type": tag, "i": i}).encode() for i in range(n)]


# -- ring codec -------------------------------------------------------------

def test_ring_roundtrip_in_seq_order(tmp_path):
    ring = flight.FlightRing(tmp_path / "r.ring",
                             capacity=flight.MIN_RING_BYTES)
    for p in _payloads(25):
        assert ring.append(p)
    ring.close()
    events, stats = flight.scan(tmp_path / "r.ring")
    assert [e["i"] for e in events] == list(range(25))
    assert stats["records"] == 25
    assert stats["torn_dropped"] == 0
    assert stats["pid"] == os.getpid()


def test_ring_wrap_keeps_newest_never_fabricates(tmp_path):
    ring = flight.FlightRing(tmp_path / "r.ring",
                             capacity=flight.MIN_RING_BYTES)
    n = 400                                    # several laps of a 4 KiB ring
    for p in _payloads(n):
        assert ring.append(p)
    assert ring.wpos > ring.capacity           # really wrapped
    ring.close()
    events, stats = flight.scan(tmp_path / "r.ring")
    idx = [e["i"] for e in events]
    assert idx, "a wrapped ring must retain its newest lap"
    assert idx == sorted(idx)                  # seq order survives the lap
    assert idx[-1] == n - 1                    # the newest record survives
    assert set(idx) <= set(range(n))           # nothing fabricated
    assert len(idx) < n                        # old laps were overwritten


def test_ring_torn_tail_dropped_not_raised(tmp_path):
    path = tmp_path / "r.ring"
    ring = flight.FlightRing(path, capacity=flight.MIN_RING_BYTES)
    for p in _payloads(10):
        ring.append(p)
    last_total = flight.RECORD_HEADER.size + len(_payloads(10)[-1])
    ring.close()
    blob = bytearray(path.read_bytes())
    # Cut the kill into the LAST record's body: zero its final bytes.
    start = flight.HEADER_SIZE + (ring.wpos % ring.capacity) - 3
    blob[start:start + 3] = b"\0\0\0"
    assert last_total > 3
    path.write_bytes(bytes(blob))
    events, stats = flight.scan(path)
    assert [e["i"] for e in events] == list(range(9))
    assert stats["torn_dropped"] >= 1


def test_ring_scan_resyncs_past_mid_damage(tmp_path):
    path = tmp_path / "r.ring"
    ring = flight.FlightRing(path, capacity=flight.MIN_RING_BYTES)
    sizes = []
    for p in _payloads(12):
        ring.append(p)
        sizes.append(flight.RECORD_HEADER.size + len(p))
    ring.close()
    blob = bytearray(path.read_bytes())
    # Garbage over record #5's body (marker left intact -> CRC fails and
    # the scanner must resync to #6, not abort the lap).
    off = flight.HEADER_SIZE + sum(sizes[:5]) + flight.RECORD_HEADER.size
    blob[off:off + 4] = b"\x7f\x7f\x7f\x7f"
    path.write_bytes(bytes(blob))
    events, stats = flight.scan(path)
    got = [e["i"] for e in events]
    assert 5 not in got
    assert set(range(12)) - set(got) == {5}
    assert stats["torn_dropped"] >= 1


def test_ring_oversize_payload_dropped_not_written(tmp_path):
    ring = flight.FlightRing(tmp_path / "r.ring",
                             capacity=flight.MIN_RING_BYTES)
    big = json.dumps({"type": "big",
                      "blob": "x" * (ring.capacity //
                                     flight.OVERSIZE_DIVISOR)}).encode()
    assert not ring.append(big)
    assert ring.append(_payloads(1)[0])
    assert ring.position()["dropped_oversize"] == 1
    ring.close()
    events, _ = flight.scan(tmp_path / "r.ring")
    assert [e["type"] for e in events] == ["ev"]


def test_ring_scan_tolerates_missing_and_garbage_files(tmp_path):
    events, stats = flight.scan(tmp_path / "absent.ring")
    assert events == [] and stats["records"] == 0
    bad = tmp_path / "bad.ring"
    bad.write_bytes(b"not a flight ring at all")
    events, stats = flight.scan(bad)
    assert events == [] and stats["records"] == 0


def test_ring_min_capacity_enforced(tmp_path):
    with pytest.raises(ValueError):
        flight.FlightRing(tmp_path / "r.ring",
                          capacity=flight.MIN_RING_BYTES - 1)


# -- the third sink ---------------------------------------------------------

def test_install_routes_obs_emits_uninstall_restores(tmp_path):
    fdir = str(tmp_path / "f")
    assert _spans.flight_sink() is None
    sink = flight.install(fdir, ring_bytes=flight.MIN_RING_BYTES)
    try:
        assert _spans.flight_sink() is sink
        # No recorder active: the ring still sees the emit (the whole
        # point — the flight sink outlives/undercuts the recorder).
        obs.emit("flight_test_marker", k=1)
        obs.counter("flight.test_counter")
    finally:
        flight.uninstall()
    assert _spans.flight_sink() is None
    rings = flight.scan_dir(fdir)
    assert len(rings) == 1
    types = [e["type"] for e in rings[0]["events"]]
    assert "flight_test_marker" in types
    assert "counter" in types
    sc = rings[0]["sidecar"]
    assert sc is not None and sc["pid"] == os.getpid()
    assert "env" in sc and "ring" in sc


def test_install_from_env_channel(tmp_path):
    assert flight.install_from_env({}) is None
    fdir = str(tmp_path / "envf")
    sink = flight.install_from_env({flight.ENV_VAR: fdir})
    try:
        assert sink is not None
        assert os.path.exists(flight.ring_path(fdir))
    finally:
        flight.uninstall()


def test_sidecar_tracks_active_traces_and_heartbeat(tmp_path):
    fdir = str(tmp_path / "f")
    sink = flight.FlightSink(fdir, ring_bytes=flight.MIN_RING_BYTES,
                             sidecar_every_s=0.0)
    sink.on_event("serve_admit", {"trace": "aa", "id": 1})
    sink.on_event("serve_admit", {"trace": "bb", "id": 2})
    sink.on_event("serve_batch", {"requests": 2, "traces": ["aa", "bb"]})
    sink.on_event("serve_request", {"trace": "aa", "status": "ok"})
    sink.close()
    sc = flight.read_sidecar(flight.sidecar_path(fdir))
    assert sc["active_traces"] == ["bb"]       # aa closed by its terminal
    assert sc["last_heartbeat_unix"] is not None
    assert sc["ring"]["seq"] == 4


def test_flight_off_is_off(tmp_path):
    """flight_dir=None: no sink installed, no ring files, /snapshot says
    not recording — the byte-identical-off contract's observable half."""
    assert ServeConfig().flight_dir is None
    assert _spans.flight_sink() is None
    assert gexport.flight_status() == {"recording": False}
    assert flight.scan_dir(str(tmp_path)) == []


# -- post-mortem bundles ----------------------------------------------------

def _armed_ring(tmp_path, n_events=6):
    fdir = str(tmp_path / "f")
    sink = flight.FlightSink(fdir, ring_bytes=flight.MIN_RING_BYTES,
                             sidecar_every_s=0.0)
    sink.on_event("serve_admit", {"trace": "t1", "id": 1})
    for i in range(n_events - 2):
        sink.on_event("serve_batch", {"requests": 1, "traces": ["t1"],
                                      "i": i})
    sink.on_event("serve_admit", {"trace": "t2", "id": 2})
    sink.close()
    return fdir


def test_capture_check_info_roundtrip(tmp_path):
    fdir = _armed_ring(tmp_path)
    bdir = postmortem.default_bundles_dir(fdir)
    path = postmortem.capture_bundle(bdir, "manual", flight_dir=fdir,
                                     extra={"why": "test"})
    assert path is not None
    assert postmortem.latest_bundle(bdir) == path
    assert postmortem.list_bundles(bdir) == [path]
    doc = postmortem.read_bundle(path)
    assert postmortem.check_bundle(doc) == []
    assert doc["cause"] == "manual"
    assert doc["detail"] == {"why": "test"}
    assert len(doc["flight"]["rings"]) == 1
    open_ids = {t["trace"] for t in doc["open_traces"]}
    assert {"t1", "t2"} <= open_ids
    info = postmortem.bundle_info(path)
    assert info["cause"] == "manual"
    assert info["pid"] == os.getpid()
    assert abs(info["time_unix"] - doc["time_unix"]) < 0.01


def test_check_bundle_rejects_tampered_attribution(tmp_path):
    fdir = _armed_ring(tmp_path)
    path = postmortem.capture_bundle(
        postmortem.default_bundles_dir(fdir), "manual", flight_dir=fdir)
    doc = postmortem.read_bundle(path)
    bad = dict(doc, cause="dog_ate_it")
    assert any("unknown cause" in p for p in postmortem.check_bundle(bad))
    plural = dict(doc)
    plural["causes"] = ["manual", "slo_alert"]
    assert any("exactly one cause" in p
               for p in postmortem.check_bundle(plural))
    noid = dict(doc, captured_by={})
    assert any("captured_by.pid" in p for p in postmortem.check_bundle(noid))


def test_trigger_throttles_per_cause_and_disarms(tmp_path):
    fdir = _armed_ring(tmp_path)
    bdir = postmortem.default_bundles_dir(fdir)
    assert postmortem.trigger("manual") is None     # not armed yet
    postmortem.install_trigger(bdir, flight_dir=fdir)
    first = postmortem.trigger("manual", note="one")
    assert first is not None
    assert postmortem.trigger("manual", note="two") is None   # throttled
    other = postmortem.trigger("slo_alert")         # per-CAUSE throttle
    assert other is not None and other != first
    postmortem.uninstall_trigger()
    assert postmortem.trigger("manual") is None     # disarmed


# -- unclean resume capture -------------------------------------------------

def _stranded_journal(jd, n_live=3):
    """A journal whose process died mid-work: admits with no terminals."""
    jr = durable.RequestJournal(jd, fsync_batch=1, rotate_records=10_000)
    rng = np.random.default_rng(258458)
    for i in range(n_live):
        a = rng.standard_normal((8, 8))
        a[np.arange(8), np.arange(8)] += 8.0
        jr.append_admit(id=i, request_id=f"r{i}", trace=f"t{i}", a=a,
                        b=rng.standard_normal(8), was_vector=True,
                        deadline_unix=None, dtype=None, structure=None)
    jr.close()
    return jr


def test_unclean_resume_captures_bundle_before_replay(shared_cache,
                                                      tmp_path):
    jd = str(tmp_path / "j")
    fdir = str(tmp_path / "f")
    _stranded_journal(jd, n_live=3)
    cfg = ServeConfig(ladder=(16,), max_batch=4, panel=16, refine_steps=1,
                      verify_gate=GATE, journal_dir=jd,
                      flight_dir=fdir,
                      flight_ring_bytes=flight.MIN_RING_BYTES)
    srv = SolverServer(cfg, cache=shared_cache).start()
    try:
        assert srv.last_resume["replayed"] == 3
    finally:
        srv.stop(drain=True, timeout=120.0)
    assert _spans.flight_sink() is None        # stop() tore the sink down
    bundle = postmortem.latest_bundle(postmortem.default_bundles_dir(fdir))
    assert bundle is not None
    doc = postmortem.read_bundle(bundle)
    assert doc["cause"] == "unclean_resume"
    assert postmortem.check_bundle(doc) == []
    # Captured BEFORE replay: the bundle's journal tail still shows every
    # stranded admit as live — the death, not the recovery.
    live_ids = sorted(a["id"] for a in doc["journal"]["live_admits"])
    assert live_ids == [0, 1, 2]
    # ...and the admits carry NO operands (debugging artifact, not replay
    # source).
    assert all("a" not in a and "b" not in a
               for a in doc["journal"]["live_admits"])
    # The resume itself completed: every stranded admit reached a terminal.
    st = durable.scan(jd)
    assert sorted(st.terminals) == [0, 1, 2]
    assert gdebug.main([bundle, "--check"]) == 0


# -- crash-spanning trace folding -------------------------------------------

def test_fold_ring_events_completes_crash_spanning_trace():
    ring_events = [
        {"type": "serve_admit", "trace": "tt", "id": 7, "n": 16,
         "tu": 100.0},
        {"type": "serve_batch", "traces": ["tt"], "requests": 1,
         "tu": 100.5},
        {"type": "gauge", "name": "serve.queue_depth", "value": 1.0,
         "tu": 100.6},                         # non-stage ring noise
    ]
    stream = [
        {"type": "serve_request", "trace": "tt", "id": 7, "status": "ok",
         "latency_s": 0.2, "t": 101.0},
    ]
    folded = requesttrace.fold_ring_events(stream, ring_events)
    assert [e["type"] for e in folded] == ["serve_admit", "serve_batch",
                                          "serve_request"]
    trees = requesttrace.request_traces(folded)
    assert set(trees) == {"tt"}
    assert requesttrace.check_traces(trees) == []
    # Duplicates fold to one stage: both sinks saw the admit.
    folded2 = requesttrace.fold_ring_events(
        [dict(ring_events[0], t=100.0)] + stream, ring_events)
    admits = [e for e in folded2 if e["type"] == "serve_admit"]
    assert len(admits) == 1


# -- /snapshot + Prometheus surfaces ----------------------------------------

def test_flight_status_and_prometheus_surfaces(tmp_path):
    fdir = str(tmp_path / "f")
    flight.install(fdir, ring_bytes=flight.MIN_RING_BYTES)
    try:
        obs.emit("serve_batch", requests=1, traces=["t1"])
        path = postmortem.capture_bundle(
            postmortem.default_bundles_dir(fdir), "manual",
            flight_dir=fdir)
        assert path is not None
        fl = gexport.flight_status()
        assert fl["recording"] and fl["flight_dir"] == fdir
        assert fl["ring"]["seq"] >= 1
        assert fl["last_bundle"]["cause"] == "manual"
        text = gexport.render_prometheus(
            {"uptime_s": 1.0, "counters": {}, "gauges": {}, "windows": {}},
            flight=fl)
        assert "gauss_flight_recording 1" in text
        assert 'gauss_postmortem_last_age_s{cause="manual"}' in text
    finally:
        flight.uninstall()


# -- gauss-debug CLI --------------------------------------------------------

def test_gauss_debug_reconstruct_and_cli(tmp_path, capsys):
    fdir = _armed_ring(tmp_path, n_events=9)
    bdir = postmortem.default_bundles_dir(fdir)
    path = postmortem.capture_bundle(bdir, "manual", flight_dir=fdir)
    doc = postmortem.read_bundle(path)
    rec = gdebug.reconstruct(doc, batches=5)
    assert rec["cause"] == "manual"
    assert len(rec["last_batches"]) == 5       # last 5 of the 7 batches
    assert all("t1" in (ev.get("traces") or ()) for ev in
               rec["last_batches"])
    # TARGET resolution: bundle file, bundles dir, flight dir all work.
    for target in (path, bdir, fdir):
        assert gdebug.resolve_bundle(target) == path
    assert gdebug.main([path, "--check"]) == 0
    capsys.readouterr()
    assert gdebug.main([fdir, "--json", "--batches", "3"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["cause"] == "manual"
    assert len(out["last_batches"]) == 3
    # A tampered bundle fails --check with a named problem.
    bad = dict(doc, cause="gremlins")
    badpath = os.path.join(bdir, "bundle-0000000000001-gremlins-1.json")
    with open(badpath, "w") as f:
        json.dump(bad, f)
    assert gdebug.main([badpath, "--check"]) == 1
    assert "problem(s)" in capsys.readouterr().out
    # Missing target exits 2.
    assert gdebug.main([str(tmp_path / "nope.json")]) == 2


def test_gauss_debug_manual_capture_flag(tmp_path, capsys):
    fdir = _armed_ring(tmp_path)
    assert gdebug.main([fdir, "--capture"]) == 0
    capsys.readouterr()
    bundle = postmortem.latest_bundle(postmortem.default_bundles_dir(fdir))
    assert postmortem.bundle_info(bundle)["cause"] == "manual"
    assert gdebug.main([bundle, "--check"]) == 0


def test_debug_entry_point_registered():
    with open(os.path.join(os.path.dirname(__file__), os.pardir,
                           "pyproject.toml")) as f:
        text = f.read()
    assert 'gauss-debug = "gauss_tpu.obs.debug:main"' in text
