"""Cross-engine agreement: the reference's strongest testing idea (SURVEY.md
§4.2-4.3 — identical results across all parallel versions) applied across
EVERY gauss engine in this framework on one random system."""

import numpy as np
import pytest

from gauss_tpu import native
from gauss_tpu.cli import _common
from gauss_tpu.verify import checks


def test_all_gauss_engines_agree():
    rng = np.random.default_rng(11)
    n = 72
    a = rng.standard_normal((n, n)) + n * np.eye(n)  # well-conditioned
    x_true = rng.standard_normal(n)
    b = a @ x_true

    backends = ["tpu", "tpu-unblocked", "tpu-rowelim", "tpu-dist",
                "tpu-dist2d"]
    if native.available():
        backends += ["seq", "omp", "threads", "forkjoin", "tiled"]

    solutions = {}
    for backend in backends:
        x, _ = _common.solve_with_backend(a, b, backend, nthreads=4,
                                          pivoting="partial")
        solutions[backend] = np.asarray(x, np.float64)
        err = checks.max_rel_error(solutions[backend], x_true)
        assert err < 1e-3, (backend, err)

    # Pairwise epsilon agreement vs the oracle engine (the reference's
    # cross-version comparison, run across ten engines instead of eyeballs).
    ref = solutions["tpu-unblocked"]
    for backend, x in solutions.items():
        assert checks.elementwise_match(x, ref, epsilon=1e-3), backend
