"""Cross-engine agreement: the reference's strongest testing idea (SURVEY.md
§4.2-4.3 — identical results across all parallel versions) applied across
EVERY gauss engine in this framework, on a random system, on the real
matrix_10 dataset file, and on the reference's own n=512 synthetic
benchmark system (VERDICT round 1 weak #6; the larger real matrices are
covered in tests/test_reference_data.py)."""

import numpy as np
import pytest

from gauss_tpu import native
from gauss_tpu.cli import _common
from gauss_tpu.verify import checks


def _all_backends():
    """Derived from the CLI's authoritative list so an engine added there is
    automatically covered here (device engines always; non-tpu ones are the
    native C++ engines, included when the library is built)."""
    backends = [b for b in _common.GAUSS_BACKENDS if b.startswith("tpu")]
    if native.available():
        backends += [b for b in _common.GAUSS_BACKENDS
                     if not b.startswith("tpu")]
    return backends


def _solve_all(a, b):
    return {backend: np.asarray(
        _common.solve_with_backend(a, b, backend, nthreads=4,
                                   pivoting="partial")[0], np.float64)
        for backend in _all_backends()}


def test_all_gauss_engines_agree():
    rng = np.random.default_rng(11)
    n = 72
    a = rng.standard_normal((n, n)) + n * np.eye(n)  # well-conditioned
    x_true = rng.standard_normal(n)
    b = a @ x_true

    solutions = _solve_all(a, b)
    for backend, x in solutions.items():
        err = checks.max_rel_error(x, x_true)
        assert err < 1e-3, (backend, err)

    # Pairwise epsilon agreement vs the oracle engine (the reference's
    # cross-version comparison, run across twelve engines instead of
    # eyeballs).
    ref = solutions["tpu-unblocked"]
    for backend, x in solutions.items():
        assert checks.elementwise_match(x, ref, epsilon=1e-3), backend


def test_all_gauss_engines_agree_real_matrix_10():
    """The reference's smallest dataset file, read in place: every engine
    must reproduce the external oracle's manufactured solution exactly to
    the CUDA epsilon (SURVEY §4.2's per-matrix error-agreement bar)."""
    from gauss_tpu.io import reference_data

    if not reference_data.available():
        pytest.skip("no reference checkout")
    a = reference_data.load_dense("matrix_10")
    n = a.shape[0]
    x_true = np.arange(1, n + 1, dtype=np.float64)
    b = a @ x_true
    for backend, x in _solve_all(a, b).items():
        assert checks.max_rel_error(x, x_true) < 1e-4, backend
        assert checks.elementwise_match(x, x_true), backend


@pytest.mark.slow
def test_all_gauss_engines_internal_512():
    """The reference's own synthetic benchmark system at n=512: every engine
    must produce the VERIFY pattern (-0.5, 0, ..., 0, 0.5) — the internal
    programs' compile-time oracle, run across the whole engine grid."""
    from gauss_tpu.io import synthetic

    a = synthetic.internal_matrix(512)
    b = synthetic.internal_rhs(512)
    for backend, x in _solve_all(a, b).items():
        assert checks.internal_pattern_ok(x, atol=1e-3), backend
