"""Golden tests on the REAL reference dataset matrices, read in place.

VERDICT round 1 #2: the reference's entire external-input evaluation runs on
actual Harwell-Boeing matrices; round 1 only exercised same-shape synthetic
stand-ins. These tests parse the real files from the read-only reference
checkout (never copied into the repo) and assert that every solver meets the
external programs' always-on oracle (max relative error vs the manufactured
solution X__[i] = i+1; reference gauss_external_input.c:304-315) at the
BASELINE.json 1e-4 bar on the real conditioning, not the deliberately easy
stand-ins.

On machines without a reference checkout the whole module skips.
"""

import numpy as np
import pytest

from gauss_tpu.io import datasets, reference_data
from gauss_tpu.verify import checks

pytestmark = pytest.mark.skipif(
    not reference_data.available(),
    reason="no reference checkout (set GAUSS_TPU_REFERENCE_ROOT)")

BAR = 1e-4  # BASELINE.json / reference EPSILON acceptance bar


def _system(name, dtype=np.float64):
    a = reference_data.load_dense(name, dtype=dtype)
    x_true = np.arange(1, a.shape[0] + 1, dtype=np.float64)
    return a, a @ x_true, x_true


def test_all_seven_real_files_found():
    for name in reference_data.REAL_NAMES:
        path = reference_data.find_dat(name)
        assert path is not None, name
        assert path.startswith(str(reference_data.reference_root()))


def test_real_headers_match_registry():
    """The registry's (n, nnz) rows were transcribed from the real headers;
    parse each real file's header and confirm (guards both directions)."""
    for name in reference_data.REAL_NAMES:
        with open(reference_data.find_dat(name)) as f:
            n, n2, nnz = (int(t) for t in f.readline().split()[:3])
        assert (n, nnz) == datasets.REGISTRY[name], name
        assert n == n2


def test_dataset_dense_source_resolution():
    assert datasets.resolve_source("jpwh_991", "auto") == "reference"
    assert datasets.resolve_source("jpwh_991", "standin") == "standin"
    # matrix_2000 is stripped from the mirror: auto falls back to stand-in.
    assert datasets.resolve_source("matrix_2000", "auto") == "standin"
    with pytest.raises(KeyError):
        datasets.resolve_source("matrix_2000", "reference")
    with pytest.raises(ValueError):
        datasets.resolve_source("jpwh_991", "bogus")
    a_ref = datasets.dataset_dense("matrix_10", source="reference")
    a_std = datasets.dataset_dense("matrix_10", source="standin")
    # matrix_10 is the generator family in both worlds: identical content.
    np.testing.assert_array_equal(a_ref, a_std)


def test_real_matrix_10_is_generator_output():
    """matrix_10.dat is matrix_gen output: value = row<col ? 2*row : 2*col
    with 1-indexed loop variables (matrix_gen.cc:15-19), i.e.
    a[i, j] = 2 * (min(i, j) + 1) in 0-indexed terms."""
    a = reference_data.load_dense("matrix_10")
    n = a.shape[0]
    i, j = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
    np.testing.assert_array_equal(a, 2.0 * (np.minimum(i, j) + 1))


@pytest.mark.parametrize("name", ["matrix_10", "jpwh_991"])
def test_oracle_solve_real_matrix(name):
    """The pure-JAX oracle (f64 on CPU) reproduces the manufactured solution
    on the real matrices — the reference's sequential-program bar."""
    from gauss_tpu.core.gauss import gauss_solve

    a, b, x_true = _system(name)
    x = np.asarray(gauss_solve(a, b, pivoting="partial"), np.float64)
    assert checks.max_rel_error(x, x_true) < BAR


@pytest.mark.parametrize("name", ["matrix_10", "jpwh_991", "orsreg_1"])
def test_refined_solve_real_matrix(name):
    """f32 blocked factorization + refinement meets the 1e-4 bar on real
    conditioning (the round-1 stand-ins could not test this)."""
    from gauss_tpu.core.blocked import solve_refined

    a, b, x_true = _system(name)
    x, _ = solve_refined(a, b, iters=3)
    assert checks.max_rel_error(x, x_true) < BAR


@pytest.mark.slow
@pytest.mark.parametrize("name", ["sherman5", "saylr4", "sherman3"])
def test_refined_solve_real_matrix_large(name):
    from gauss_tpu.core.blocked import solve_refined

    a, b, x_true = _system(name)
    x, _ = solve_refined(a, b, iters=5, tol=1e-6)
    assert checks.max_rel_error(x, x_true) < BAR


@pytest.mark.slow
def test_dist_engines_real_matrix():
    """The distributed engines on the 8-virtual-device mesh solve a real
    matrix to the same bar (round 1 ran them only on synthetics)."""
    from gauss_tpu.dist import gauss_dist, gauss_dist2d, make_mesh
    from gauss_tpu.dist.mesh import make_mesh_2d

    a, b, x_true = _system("jpwh_991")
    x = np.asarray(gauss_dist.gauss_solve_dist(
        a.astype(np.float64), b.astype(np.float64), mesh=make_mesh(8)))
    assert checks.max_rel_error(x, x_true) < BAR
    x2 = np.asarray(gauss_dist2d.gauss_solve_dist2d(
        a.astype(np.float64), b.astype(np.float64), mesh=make_mesh_2d(4, 2)))
    assert checks.max_rel_error(x2, x_true) < BAR


@pytest.mark.slow
def test_cross_engine_agreement_real_matrix():
    """SURVEY §4.2's bar on a real matrix: every engine reproduces the
    external oracle at 1e-4, and all engines agree pairwise on normalized
    solutions within 2x that bar — the triangle-inequality implication of
    the per-engine oracle bar, which holds across precision families (f32
    device engines vs f64 native engines follow different rounding paths,
    so exact agreement is only guaranteed vs the shared truth)."""
    from gauss_tpu import native
    from gauss_tpu.cli import _common

    a, b, x_true = _system("jpwh_991")
    backends = ["tpu", "tpu-unblocked", "tpu-dist", "tpu-dist2d",
                "tpu-dist-blocked"]
    if native.available():
        backends += ["seq", "omp", "threads", "forkjoin", "tiled"]
    sols = {}
    for backend in backends:
        x, _ = _common.solve_with_backend(a, b, backend, nthreads=4)
        sols[backend] = np.asarray(x, np.float64)
        assert checks.max_rel_error(sols[backend], x_true) < BAR, backend
    ref = sols["tpu-unblocked"]
    scale = float(np.abs(ref).max())
    for backend, x in sols.items():
        assert checks.elementwise_match(x / scale, ref / scale,
                                        epsilon=2 * BAR), backend
