"""PR 11 — mixed-precision factorization: bf16/bf16x3 MXU paths refined
back to the 1e-4 gate, plus the batched throughput record.

Covers the precision contract (f32 accumulation, f32 inverses/solves,
doubled VMEM admission at bf16), the dtype-parameterized residual grid
over the fused/unfused factorization forms, refine-convergence with the
typed demotion ladder (core.lowered), the surfaced refine_ds iteration
count, the tuned (dtype, refine_steps) axis, the serve layer's dtype
lanes with cache-key isolation, and the throughput bench's
record/ratchet machinery.
"""

import json
import os
import tempfile

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from gauss_tpu.core import blocked, dsfloat, lowered  # noqa: E402
from gauss_tpu.core.matmul import (  # noqa: E402
    BF16X3,
    dot_bf16x3,
    resolve_precision,
    split_bf16,
)
from gauss_tpu.verify import checks  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def rng():
    return np.random.default_rng(258458)


def _system(rng, n):
    a = rng.standard_normal((n, n))
    a[np.arange(n), np.arange(n)] += float(n)
    return a, rng.standard_normal(n)


def _ill_system(rng, n, cond_exp=6):
    """Symmetric system with condition ~10^cond_exp — bf16 refinement
    (contraction ~cond * 4e-3) must fail on it while f32 + double-single
    still clears the gate (the saylr4 class)."""
    q, _ = np.linalg.qr(rng.standard_normal((n, n)))
    d = np.logspace(0, cond_exp, n)
    return (q * d) @ q.T, rng.standard_normal(n)


# --- the precision contract ------------------------------------------------


def test_accumulate_contract_inverses_and_solves(rng):
    """bf16 factors store f32 diagonal-block inverses and solve in f32
    (returning f32); the f32 path keeps f32 everywhere — the contract's
    observable surface."""
    a, b = _system(rng, 64)
    fac16 = blocked.lu_factor_blocked(jnp.asarray(a, jnp.bfloat16),
                                      panel=16)
    assert fac16.m.dtype == jnp.bfloat16
    assert fac16.linv.dtype == jnp.float32
    assert fac16.uinv.dtype == jnp.float32
    x = blocked.lu_solve(fac16, jnp.asarray(b, jnp.float32))
    assert x.dtype == jnp.float32
    # One-shot bf16 accuracy lands at storage rounding, not accumulated
    # rounding: comfortably under 1e-2 relative for a dominant system.
    rel = checks.residual_norm(a, np.asarray(x, np.float64), b,
                               relative=True)
    assert rel < 5e-3
    fac32 = blocked.lu_factor_blocked(jnp.asarray(a, jnp.float32), panel=16)
    assert fac32.m.dtype == jnp.float32
    assert fac32.linv.dtype == jnp.float32


def test_bf16x3_split_gemm_fidelity(rng):
    """The explicit split-GEMM: ~1e-5 relative class (lax.Precision.HIGH's
    fidelity), two orders tighter than a plain bf16 pass; the split is an
    exact two-term decomposition to bf16-pair precision."""
    a = rng.standard_normal((256, 256)).astype(np.float32)
    b = rng.standard_normal((256, 256)).astype(np.float32)
    ref = a.astype(np.float64) @ b.astype(np.float64)
    scale = np.abs(ref).max()
    e3 = np.abs(np.asarray(dot_bf16x3(jnp.asarray(a), jnp.asarray(b)),
                           np.float64) - ref).max() / scale
    e1 = np.abs(np.asarray(
        jnp.dot(jnp.asarray(a, jnp.bfloat16), jnp.asarray(b, jnp.bfloat16)),
        np.float64) - ref).max() / scale
    assert e3 < 5e-5
    assert e3 < e1 / 30
    hi, lo = split_bf16(jnp.asarray(a))
    recon = np.asarray(hi, np.float32) + np.asarray(lo, np.float32)
    assert np.abs(recon - a).max() <= 2 ** -14  # ~16 captured bits


def test_bf16x3_precision_name_is_opt_in():
    """resolve_precision admits "bf16x3" only where the caller routes the
    sentinel (blocked LU, matmul); everywhere else it is a typed error,
    never a raw trace failure."""
    assert resolve_precision("bf16x3", allow_split=True) == BF16X3
    with pytest.raises(ValueError, match="bf16x3"):
        resolve_precision("bf16x3")
    with pytest.raises(ValueError, match="unknown precision"):
        resolve_precision("bf16x9", allow_split=True)


def test_fused_fits_vmem_bf16_admits_double(monkeypatch):
    """Halving itemsize roughly doubles the fused kernel's admission:
    the largest h admitted at itemsize=2 is >= 1.8x the itemsize=4 one
    (exact 2x minus the itemsize-independent per-row overhead)."""
    def max_h(itemsize):
        lo, hi = 128, 1 << 22
        while lo < hi:
            mid = (lo + hi + 1) // 2
            if blocked.fused_fits_vmem(mid, 128, ct=256,
                                       itemsize=itemsize):
                lo = mid
            else:
                hi = mid - 1
        return lo

    h4, h2 = max_h(4), max_h(2)
    assert h2 > h4
    assert h2 / h4 >= 1.8


def test_abft_rejects_lowered_typed(rng):
    """The checksum rider is defined against f32 math: bf16 storage and
    the bf16x3 split both get the clear ValueError, on the flat and the
    chunked forms."""
    a, _ = _system(rng, 64)
    a16 = jnp.asarray(a, jnp.bfloat16)
    with pytest.raises(ValueError, match="abft=True requires float32"):
        blocked.lu_factor_blocked(a16, panel=16, abft=True)
    with pytest.raises(ValueError, match="abft=True requires float32"):
        blocked.lu_factor_blocked_chunked(jnp.asarray(a, jnp.float32),
                                          panel=16, chunk=2,
                                          gemm_precision="bf16x3",
                                          abft=True)


# --- the dtype-parameterized residual grid ---------------------------------


@pytest.mark.parametrize("dtype", ["bfloat16", "bf16x3"])
@pytest.mark.parametrize("n,panel,chunk", [
    (96, 16, 2), (100, 16, 2),   # non-multiple-of-panel edge
    (64, 32, 1),                 # single-panel groups (fused skipped)
    (96, 48, 2),                 # panel not dividing n
])
def test_lowered_residual_grid(rng, dtype, n, panel, chunk):
    """The lowered analog of test_fused's f32 grid: every factorization
    form (flat / unrolled / chunked), fused AND unfused panel impls, at
    bf16 storage and the bf16x3 split — each factor refines back under
    the SAME 1e-4 relative gate through the shared dsfloat machinery."""
    a, b = _system(rng, n)
    storage = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    gp = "bf16x3" if dtype == "bf16x3" else "highest"
    a_dev = jnp.asarray(a, storage)
    at_ds, b_ds = dsfloat.to_ds(a.T), dsfloat.to_ds(b)
    # The unfused-pair ("auto") leg runs on one representative shape —
    # the kernels share the tile math verbatim (test_fused bit-identity),
    # so per-shape coverage of both impls only re-compiles the same code.
    impls = ("fused", "auto") if (n, panel, chunk) == (96, 16, 2) \
        else ("fused",)
    for impl in impls:
        routes = [
            blocked.lu_factor_blocked(a_dev, panel=panel, panel_impl=impl,
                                      gemm_precision=gp),
            blocked.lu_factor_blocked_unrolled(a_dev, panel=panel,
                                               panel_impl=impl,
                                               gemm_precision=gp),
            blocked.lu_factor_blocked_chunked(a_dev, panel=panel,
                                              chunk=chunk, panel_impl=impl,
                                              gemm_precision=gp),
        ]
        for fac in routes:
            x0 = blocked.lu_solve(fac, b_ds.hi)
            x = dsfloat.refine_ds(fac, at_ds, b_ds, x0, iters=6)
            rel = checks.residual_norm(a, dsfloat.ds_to_f64(x), b,
                                       relative=True)
            assert rel < 1e-4, (dtype, impl, n, panel, chunk, rel)


# --- refinement convergence + typed demotion -------------------------------


def test_refine_convergence_property(rng):
    """The convergence property the ladder rests on: across seeds, a
    bf16 factor + refine_ds either meets 1e-4 or the solve demotes
    TYPED — solve_lowered_auto always ends verified, and the serving
    dtype is recorded honestly."""
    for seed in range(5):
        r = np.random.default_rng(seed)
        a, b = _system(r, 80)
        x, _, info = lowered.solve_lowered_auto(a, b)
        assert info["rel_residual"] <= 1e-4
        assert checks.residual_norm(a, x, b, relative=True) <= 1e-4
        # Untuned store: the start IS float32, so nothing can demote.
        assert info["dtype"] == "float32" and info["demoted"] is False


def test_lowered_direct_rungs(rng):
    """Each ladder dtype, called directly, converges on a dominant
    system and reports its measured refine count."""
    a, b = _system(rng, 96)
    for dt, max_steps in (("bfloat16", 4), ("bf16x3", 2), ("float32", 2)):
        x, fac, info = lowered.solve_lowered(a, b, dtype=dt)
        assert info["rel_residual"] <= 1e-4
        assert 0 <= info["refine_steps"] <= max_steps
        assert info["dtype"] == dt


def test_lowered_demotes_typed_on_ill_conditioning(rng):
    """cond ~1e6: bf16 refinement diverges -> typed
    PrecisionNotConvergedError; the auto walk demotes down the ladder
    and still serves a verified solution."""
    a, b = _ill_system(rng, 64)
    with pytest.raises(lowered.PrecisionNotConvergedError) as ei:
        lowered.solve_lowered(a, b, dtype="bfloat16")
    assert ei.value.dtype == "bfloat16"
    assert ei.value.rel_residual > 1e-4
    x, _, info = lowered.solve_lowered_auto(a, b)
    assert checks.residual_norm(a, x, b, relative=True) <= 1e-4


def test_lowered_auto_consults_tuned_store(rng, monkeypatch):
    """A tuned store that recorded a converging (bfloat16, steps) pair
    moves the start down the ladder; the served dtype is bf16 with no
    demotion on a well-conditioned operand — and an ill-conditioned one
    demotes back to f32 deterministically."""
    from gauss_tpu.tune import apply as tapply

    def fake_params(op, n, dtype="float32", engine="blocked"):
        assert op == "lowered"
        return {"dtype": "bfloat16", "refine_steps": 6}

    monkeypatch.setattr(tapply, "params_for", fake_params)
    a, b = _system(rng, 80)
    x, _, info = lowered.solve_lowered_auto(a, b)
    assert info["dtype"] == "bfloat16" and info["demoted"] is False
    assert checks.residual_norm(a, x, b, relative=True) <= 1e-4
    ill_a, ill_b = _ill_system(rng, 64)
    x, _, info = lowered.solve_lowered_auto(ill_a, ill_b)
    assert info["demoted"] is True
    assert checks.residual_norm(ill_a, x, ill_b, relative=True) <= 1e-4


def test_recovery_ladder_lowered_rung(rng, monkeypatch):
    """structured_rungs(lowered=True) prepends the mixed-precision rung
    for the dense tag only (abft wins when both are set), and solve_auto
    routes through it when the tuned store enables lowering — rung 0
    serves, not 'demoted'."""
    from gauss_tpu.resilience import recover
    from gauss_tpu.structure import router

    assert recover.structured_rungs("dense", lowered=True)[0] == "lowered"
    assert recover.structured_rungs("dense")[0] == "blocked"
    assert recover.structured_rungs("spd", lowered=True)[0] == "cholesky"
    assert recover.structured_rungs("dense", abft=True,
                                    lowered=True)[0] == "abft"
    monkeypatch.setattr(lowered, "lowered_enabled", lambda n: True)
    a, b = _system(rng, 80)
    res = router.solve_auto(a, b)
    assert res.rung == "lowered" and res.rung_index == 0
    assert res.rel_residual <= 1e-4


# --- refine_ds surfaced iteration count ------------------------------------


def test_refine_ds_surfaces_iteration_count(rng):
    """tol + return_iters: the count stops advancing at convergence and
    the converged solution matches the budget run; the default call
    shape (existing callers) is unchanged — a DS pair, same trace."""
    a, b = _system(rng, 64)
    fac = blocked.lu_factor_blocked(jnp.asarray(a, jnp.float32), panel=16)
    at_ds, b_ds = dsfloat.to_ds(a.T), dsfloat.to_ds(b)

    def x0():
        return blocked.lu_solve(fac, b_ds.hi)

    x, used = dsfloat.refine_ds(fac, at_ds, b_ds, x0(), iters=6,
                                tol=1e-5, return_iters=True)
    used = int(used)
    assert 0 <= used < 6  # dominant f32 system converges well early
    assert checks.residual_norm(a, dsfloat.ds_to_f64(x), b,
                                relative=True) < 1e-5
    # Without tol the count is the full budget.
    _, used_all = dsfloat.refine_ds(fac, at_ds, b_ds, x0(), iters=3,
                                    return_iters=True)
    assert int(used_all) == 3
    # The pre-existing call shape: a bare DS back.
    x_plain = dsfloat.refine_ds(fac, at_ds, b_ds, x0(), iters=2)
    assert isinstance(x_plain, dsfloat.DS)


# --- the tuned (dtype, refine_steps) axis ----------------------------------


def test_lowered_space_declared():
    from gauss_tpu.tune import space as tspace

    axes = {ax.name: ax for ax in tspace.space_for("lowered")}
    assert axes["dtype"].seed == "float32"  # untuned = unchanged
    assert set(axes["dtype"].candidates) == {"bfloat16", "bf16x3"}
    assert axes["refine_steps"].seed == tspace.LOWERED_REFINE_SEED
    assert tspace.seed_params("lowered")["dtype"] == "float32"


def test_tune_measurer_disqualifies_nonconverging(rng, monkeypatch):
    """The sweep can only ever pin a converging pair: a candidate that
    misses the gate at its budget returns None (disqualified), and the
    converged candidate's measured step count feeds the concretizer."""
    from gauss_tpu.tune import runner

    ill = _ill_system(np.random.default_rng(0), 48)
    monkeypatch.setattr(runner, "_seeded_system", lambda n, seed: ill)
    t = runner._measure_lowered(48, "float32",
                                {"dtype": "bfloat16", "refine_steps": 6},
                                258458, 1, None)
    assert t is None
    t = runner._measure_lowered(48, "float32",
                                {"dtype": "float32", "refine_steps": 8},
                                258458, 1, None)
    assert t is not None and t > 0
    used = runner._LOWERED_USED_STEPS[(48, "float32")]
    conc = runner._concrete_lowered(
        48, "float32", {"dtype": "float32", "refine_steps": 8})
    assert conc["refine_steps"] == min(8, max(1, used + 1))


def test_lowered_sweep_point_end_to_end(rng):
    """A micro sweep over the lowered axes picks a converging winner and
    produces a regress-ingestable point."""
    from gauss_tpu.tune import runner

    point = runner.sweep_point("lowered", 48, reps=1,
                               axes={"dtype": ["float32", "bfloat16"],
                                     "refine_steps": [6]})
    assert point["op"] == "lowered"
    assert point["best_params"]["dtype"] in ("float32", "bfloat16")
    assert point["best_s"] > 0


# --- serve: dtype lanes + cache isolation ----------------------------------


def test_cachekey_no_dtype_aliasing():
    """f32 and lowered executables can never alias: distinct keys,
    distinct entries, both solving at the gate."""
    from gauss_tpu.serve.cache import (
        BatchedExecutable,
        CacheKey,
        ExecutableCache,
        storage_dtype,
    )

    assert storage_dtype("bf16x3") == np.dtype("float32")
    assert storage_dtype("bfloat16") == np.dtype("bfloat16")
    cache = ExecutableCache(8)
    keys = [CacheKey(bucket_n=32, nrhs=1, batch=2, dtype=dt,
                     engine="blocked", refine_steps=2)
            for dt in ("float32", "bfloat16", "bf16x3")]
    assert len(set(keys)) == 3
    exes = [cache.get(k) for k in keys]
    assert len({id(e) for e in exes}) == 3 and len(cache) == 3
    rng = np.random.default_rng(0)
    a = np.stack([rng.standard_normal((32, 32)) + 32 * np.eye(32)
                  for _ in range(2)])
    b = rng.standard_normal((2, 32, 1))
    for key, exe in zip(keys, exes):
        assert isinstance(exe, BatchedExecutable)
        x = exe.solve(a, b)
        rel = max(checks.residual_norm(a[i], x[i], b[i], relative=True)
                  for i in range(2))
        assert rel <= 1e-4, (key.dtype, rel)


def test_loadgen_dtype_token():
    from gauss_tpu.serve.loadgen import parse_mix

    specs = parse_mix("random:64,dtype:bfloat16/64*2,dtype:bf16x3/32")
    assert [(s.kind, s.arg, s.dtype) for s, _ in specs] == [
        ("random", "64", None), ("random", "64", "bfloat16"),
        ("random", "32", "bf16x3")]
    assert specs[1][1] == 2.0
    with pytest.raises(ValueError, match="bad dtype"):
        parse_mix("dtype:float8/64")
    with pytest.raises(ValueError, match="bad size"):
        parse_mix("dtype:bfloat16/0")
    with pytest.raises(ValueError, match="bad size"):
        parse_mix("dtype:bfloat16")


def test_serve_dtype_lanes_end_to_end(rng):
    """A server mixing f32 and bf16 requests: same-bucket different-dtype
    requests never share a batch or an executable, every solution passes
    the verify gate, and both dtype entries exist in the cache."""
    from gauss_tpu.serve.admission import ServeConfig
    from gauss_tpu.serve.cache import ExecutableCache
    from gauss_tpu.serve.server import SolverServer

    cfg = ServeConfig(ladder=(32, 64), max_batch=4, refine_steps=2,
                      verify_gate=1e-4)
    # cache=: the exact-key-set assertion below needs isolation from the
    # process-shared default cache other tests populate.
    with SolverServer(cfg, cache=ExecutableCache(8)) as server:
        handles = []
        operands = []
        for i in range(6):
            a, b = _system(rng, 48)
            dt = "bfloat16" if i % 2 else None  # None -> cfg default f32
            operands.append((a, b))
            handles.append(server.submit(a, b, dtype=dt))
        results = [h.result(120.0) for h in handles]
        for (a, b), res in zip(operands, results):
            assert res.ok, res.error
            assert checks.residual_norm(a, res.x, b, relative=True) <= 1e-4
        key_dtypes = {k.dtype for k in server.cache.keys()}
    assert key_dtypes == {"float32", "bfloat16"}


# --- the throughput record --------------------------------------------------


def test_throughput_bench_and_ratchet(tmp_path):
    """The batched solves/sec leg: summary shape, verified-only history
    derivation, regress ingest of the kind, and the committed ratchet
    entries that gate the record from this PR on."""
    from gauss_tpu.bench import throughput as tput
    from gauss_tpu.obs import regress

    summary = tput.measure_throughput(ns=[48], batch=2, reps=1, seed=1)
    (leg,) = summary["legs"]
    assert leg["verified"] and leg["s_per_solve"] > 0
    assert leg["dtype"] == "float32" and leg["refine_steps"] == 1
    recs = tput.history_records(summary)
    assert recs == [("tput:float32/n48/b2/s_per_solve",
                     leg["s_per_solve"], "s")]
    # Unverified legs never become baselines.
    bad = dict(summary, legs=[dict(leg, verified=False)])
    assert tput.history_records(bad) == []
    # regress ingests the kind.
    p = tmp_path / "tput.json"
    p.write_text(json.dumps(summary))
    ingested = regress.ingest_file(p)
    assert [r["metric"] for r in ingested] == [recs[0][0]]
    # The record is ratcheted like the latency headline: committed
    # baselines + explicit ceilings, gated by the same evaluator.
    for n in (256, 1024, 2048):
        assert f"tput:float32/n{n}/b8/s_per_solve" in \
            regress.RATCHET_BASELINES
    assert regress.RATCHET_CEILINGS[
        "tput:float32/n2048/b8/s_per_solve"] == 1.4
    best = regress.RATCHET_BASELINES["tput:float32/n2048/b8/s_per_solve"]
    assert regress.evaluate_ratchet(
        "tput:float32/n2048/b8/s_per_solve",
        best * 1.5)["status"] == "out-of-band"
    assert regress.evaluate_ratchet(
        "tput:float32/n2048/b8/s_per_solve",
        best * 1.2)["status"] == "ok"


def test_throughput_epochs_committed():
    """3 seeded epochs per record size in the committed history (the
    acceptance artifact)."""
    from gauss_tpu.obs import regress

    hist = regress.load_history(
        os.path.join(REPO, "reports", "history.jsonl"))
    for n in (256, 1024, 2048):
        vals = [r["value"] for r in hist
                if r["metric"] == f"tput:float32/n{n}/b8/s_per_solve"]
        assert len(vals) >= 3, n
        assert min(vals) <= regress.RATCHET_BASELINES[
            f"tput:float32/n{n}/b8/s_per_solve"] * 1.0001


# --- provenance: grid --dtype metric isolation ------------------------------


def test_cell_metric_carries_dtype():
    """Lowered grid cells enter history as their own metrics; f32/absent
    keeps every pre-existing name."""
    from gauss_tpu.obs import regress

    base = {"suite": "gauss-internal", "key": "2048", "backend": "tpu",
            "span": "device"}
    assert regress._cell_metric(base) == \
        "cell:gauss-internal/2048/tpu@device"
    assert regress._cell_metric(dict(base, dtype="float32")) == \
        "cell:gauss-internal/2048/tpu@device"
    assert regress._cell_metric(dict(base, dtype="bfloat16")) == \
        "cell:gauss-internal/2048/tpu@device@bfloat16"


def test_grid_cell_dtype_field_default():
    from gauss_tpu.bench.grid import Cell

    c = Cell("gauss-internal", "64", "tpu", 1.0, True, 0.0, None)
    assert c.dtype == "float32"
