"""Attribution-plane tests (ISSUE 17): the AttributionMatrix cell/roofline/
capacity math, the util.* gauge fan-out through the live hooks, gauss-prof's
folded stacks / top tables / roofline series, per-request cost accounting
through the serving plane (and the attr=None byte-identity contract), the
summarizer's utilization section, and the ratchet-failure phase-attribution
path (regress.attribute_phases / doctor.profile_from_phases).

All CPU (conftest pins the platform); serving tests use the smallest ladder
so the jitted-executable set stays tiny.
"""

import json

import numpy as np
import pytest

from gauss_tpu import obs
from gauss_tpu.obs import attr, doctor, prof, regress, summarize
from gauss_tpu.obs import export as obs_export
from gauss_tpu.obs import live as obs_live
from gauss_tpu.serve import STATUS_OK, ServeConfig, SolverServer
from gauss_tpu.serve import loadgen

LADDER = (16, 32)

PEAKS = attr.Peaks(flops_per_s=1e9, bytes_per_s=1e10, source="env")


def _system(rng, n, k=None):
    a = rng.standard_normal((n, n))
    a[np.arange(n), np.arange(n)] += float(n)
    b = rng.standard_normal(n) if k is None else rng.standard_normal((n, k))
    return a, b


def _config(**over):
    kw = dict(ladder=LADDER, max_batch=4, panel=16, refine_steps=1,
              verify_gate=1e-4)
    kw.update(over)
    return ServeConfig(**kw)


# -- peaks + budgets --------------------------------------------------------

def test_peaks_env_override(monkeypatch):
    monkeypatch.setenv("GAUSS_PEAK_FLOPS", "2.5e12")
    monkeypatch.setenv("GAUSS_PEAK_BYTES", "8e11")
    p = attr.calibrate_peaks()
    assert p.source == "env"
    assert p.flops_per_s == 2.5e12 and p.bytes_per_s == 8e11
    assert p.to_dict()["source"] == "env"


def test_peaks_measured_is_cached_and_positive(monkeypatch):
    monkeypatch.delenv("GAUSS_PEAK_FLOPS", raising=False)
    monkeypatch.delenv("GAUSS_PEAK_BYTES", raising=False)
    p = attr.calibrate_peaks(n=64, repeats=1, refresh=True)
    assert p.source == "measured"
    assert p.flops_per_s > 0 and p.bytes_per_s > 0
    # cached: second call returns the same object, no re-measurement
    assert attr.calibrate_peaks() is p


def test_lu_budgets_analytic_math():
    n, k = 32, 2
    # factor (2/3)n^3 + one solve pass of 2 n^2 k
    assert attr.lu_flop_budget(n, k) == pytest.approx(
        (2 / 3) * n ** 3 + 2 * n * n * k)
    # refinement rounds add solve passes; batch scales linearly
    base = attr.lu_flop_budget(n, k, refine_steps=0)
    assert attr.lu_flop_budget(n, k, refine_steps=2) == pytest.approx(
        base + 2 * (2 * n * n * k))
    assert attr.lu_flop_budget(n, k, batch=3) == pytest.approx(3 * base)
    assert attr.lu_byte_budget(n, k, itemsize=4) == pytest.approx(
        (n * n + n * k) * 4 * 2)
    assert attr.lu_byte_budget(n, k, batch=2, refine_steps=1) == \
        pytest.approx((n * n + n * k) * 4 * 3 * 2)


# -- the matrix -------------------------------------------------------------

def test_matrix_cells_roofline_capacity():
    m = attr.AttributionMatrix(peaks=PEAKS)
    m.observe("serve_batch", "exe_a", 0.5, engine="blocked", lane=0,
              requests=4, flops=1e8, bytes_accessed=1e6, compile_s=0.25,
              sig="f32/b16")
    m.observe("serve_batch", "exe_a", 0.5, engine="blocked", lane=0,
              requests=4, flops=1e8, sig="f32/b16")
    m.observe("warmup", "exe_b", 0.125, engine="blocked", lane=1)
    m.observe("stream", "oc_exe", 2.0, engine="outofcore", stall_frac=0.25)

    cells = m.top_cells()
    assert cells[0]["exe"] == "oc_exe"  # sorted by device-seconds
    cell = next(c for c in cells if c["exe"] == "exe_a")
    assert cell["seconds"] == 1.0 and cell["calls"] == 2
    assert cell["requests"] == 8 and cell["compile_s"] == 0.25
    assert cell["flops"] == 2e8

    roof = m.roofline()
    assert sorted(m.engine_names()) == ["blocked", "outofcore"]
    blk = roof["blocked"]
    # 2e8 flops over 1.125 engine-seconds; frac against the 1e9 peak
    assert blk["achieved_flops_per_s"] == pytest.approx(2e8 / 1.125)
    assert blk["flops_frac"] == pytest.approx(2e8 / 1.125 / 1e9, rel=1e-4)
    assert blk["achieved_bytes_per_s"] == pytest.approx(1e6 / 1.125)
    assert "stall_frac" not in blk  # no ledger-measured stalls
    assert roof["outofcore"]["stall_frac"] == 0.25

    cap = m.capacity()
    # only serve* phases count toward the serving capacity total
    assert cap["serve_device_s"] == pytest.approx(1.0)
    sig = cap["sigs"]["f32/b16"]
    assert sig["requests"] == 8 and sig["device_s"] == pytest.approx(1.0)
    assert sig["device_s_per_request"] == pytest.approx(0.125)
    assert sig["est_requests_per_s"] == pytest.approx(8.0)
    assert set(cap["lanes"]) == {"0", "1"}

    snap = m.snapshot()
    assert snap["observes"] == 4
    assert snap["device_s_total"] == pytest.approx(3.125)
    assert snap["peaks"]["source"] == "env"


def test_matrix_forwards_attr_events_and_util_gauges():
    agg = obs_live.LiveAggregator()
    prev = obs_live.install(agg)
    try:
        m = attr.AttributionMatrix(peaks=PEAKS)
        m.observe("serve_batch", "exe", 0.25, lane=2, flops=1e6)
    finally:
        obs_live.uninstall(prev)
    snap = agg.snapshot()
    g = snap["gauges"]
    assert g["util.lane2.device_s_per_s"] > 0
    assert 0.0 <= g["util.lane2.stall_frac"] <= 1.0
    assert g["util.lane2.achieved_flops_per_s"] == pytest.approx(4e6)
    assert g["util.lane2.flops_frac"] == pytest.approx(4e6 / 1e9, rel=1e-4)
    assert g["util.blocked.achieved_flops_per_s"] == pytest.approx(4e6)
    assert "util.exec_s" in snap["windows"]


def test_install_uninstall_and_status():
    assert attr.active() is None
    assert attr.status() == {"recording": False}
    assert obs_export.attr_status() == {"recording": False}
    m = attr.AttributionMatrix(peaks=PEAKS)
    prev = attr.install(m)
    try:
        assert attr.active() is m
        st = obs_export.attr_status()
        assert st["recording"] is True and st["observes"] == 0
    finally:
        attr.uninstall(prev)
    assert attr.active() is None


# -- gauss-prof: folds + tables + roofline ----------------------------------

def _span(name, dur, parent=None):
    ev = {"type": "span", "name": name, "dur_s": dur}
    if parent:
        ev["parent"] = parent
    return ev


def test_folded_stacks_self_time_and_round_trip():
    events = [
        _span("root", 1.0),
        _span("child", 0.4, parent="root"),
        _span("leaf", 0.1, parent="child"),
        _span("child", 0.2, parent="root"),
    ]
    folds = prof.folded_stacks(events)
    # parents carry SELF time: root 1.0 - 0.6, child 0.6 - 0.1
    assert folds["root"] == pytest.approx(0.4)
    assert folds["root;child"] == pytest.approx(0.5)
    assert folds["root;child;leaf"] == pytest.approx(0.1)
    lines = prof.fold_lines(folds)
    assert lines == sorted(lines)  # deterministic order
    assert prof.fold_lines(prof.parse_folded(lines)) == lines
    # malformed lines are ignored, not fatal
    assert prof.parse_folded(["", "noval", "a;b 100"]) == {"a;b": 1e-4}


def test_top_executables_and_span_fallback():
    events = [
        {"type": "attr", "phase": "serve_batch", "exe": "exe_a", "lane": 0,
         "engine": "blocked", "seconds": 0.3, "requests": 2, "flops": 5.0},
        {"type": "attr", "phase": "serve_batch", "exe": "exe_a", "lane": 0,
         "engine": "blocked", "seconds": 0.2, "requests": 1},
        {"type": "attr", "phase": "warmup", "exe": "exe_b", "lane": 1,
         "engine": "blocked", "seconds": 0.1, "requests": 1},
    ]
    rows = prof.top_executables(events, 10)
    assert [r["exe"] for r in rows] == ["exe_a", "exe_b"]
    assert rows[0]["seconds"] == pytest.approx(0.5)
    assert rows[0]["requests"] == 3 and rows[0]["calls"] == 2
    # streams that predate the plane fall back to span-name totals
    rows = prof.top_executables([_span("factor", 0.2), _span("factor", 0.1)])
    assert rows[0]["phase"] == "factor"
    assert rows[0]["seconds"] == pytest.approx(0.3)


def test_roofline_series_reads_peaks_from_stream():
    events = [
        {"type": "attr_plane", "event": "start", "flops_per_s": 1e9,
         "bytes_per_s": 1e10, "source": "env"},
        {"type": "attr", "phase": "serve_batch", "exe": "e", "lane": 0,
         "engine": "blocked", "seconds": 0.5, "requests": 1, "flops": 1e8,
         "bytes": 1e6},
        {"type": "attr", "phase": "stream", "exe": "oc", "lane": 0,
         "engine": "outofcore", "seconds": 1.0, "requests": 1,
         "stall_frac": 0.5},
    ]
    roof = prof.roofline_series(events)
    assert roof["blocked"]["achieved_flops_per_s"] == pytest.approx(2e8)
    # fractions divide by the peaks the STREAM recorded, not a fresh local
    # calibration — the run's own ceiling is the honest denominator
    assert roof["blocked"]["flops_frac"] == pytest.approx(0.2)
    assert roof["blocked"]["bytes_frac"] == pytest.approx(2e6 / 1e10)
    assert roof["outofcore"]["stall_frac"] == pytest.approx(0.5)


# -- cost accounting through the serve plane --------------------------------

def test_serve_result_cost_fields_with_attr_on(rng):
    with SolverServer(_config(attr=True)) as srv:
        assert srv.attr is not None
        handles = [srv.submit(*_system(rng, 24)) for _ in range(3)]
        results = [h.result(60.0) for h in handles]
        assert all(r.status == STATUS_OK for r in results)
        # every served request carries its device-seconds share; compile
        # seconds amortize over the batch that paid them
        assert all(isinstance(r.device_s, float) and r.device_s > 0
                   for r in results)
        assert all(isinstance(r.compile_s, float) and r.compile_s >= 0
                   for r in results)
        cap = srv.attr.capacity()
        assert cap["serve_device_s"] > 0
        assert cap["sigs"]  # per-compat-sig capacity model populated
        for row in cap["sigs"].values():
            assert row["device_s_per_request"] > 0
            assert row["est_requests_per_s"] > 0
    # server stop uninstalls the plane
    assert attr.active() is None


def test_serve_attr_off_is_byte_identical(rng, tmp_path):
    stream = tmp_path / "plain.jsonl"
    with obs.run(metrics_out=str(stream), tool="t"):
        with SolverServer(_config()) as srv:
            assert srv.attr is None
            r = srv.submit(*_system(rng, 20)).result(60.0)
            assert r.status == STATUS_OK
            # the byte-identity contract: no cost fields, no lane
            # device_s key, no attr/attr_plane events on the stream
            assert r.device_s is None and r.compile_s is None
            if srv._lanes is not None:
                for ln in srv._lanes.stats():
                    assert "device_s" not in ln
    text = stream.read_text()
    assert '"attr"' not in text and '"attr_plane"' not in text
    assert '"device_s"' not in text and '"cost"' not in text


@pytest.mark.slow
def test_loadgen_cost_section_reconciles(rng):
    cfg = _config(attr=True, max_queue=64)
    lg = loadgen.LoadgenConfig(mix="random:20*2,random:24", requests=12,
                               warmup=2, mode="closed", concurrency=2,
                               seed=7, verify_gate=1e-4, serve=cfg)
    with SolverServer(cfg) as srv:
        summary = loadgen.run_load(srv, lg)
    cost = summary["cost"]
    assert cost["request_device_s"] > 0
    assert cost["device_s_per_request"] > 0
    # the reconcile identity prof-check gates: client-visible device cost
    # (served + warmup) equals the matrix's serve-phase total
    req = cost["request_device_s"] + cost["warmup_device_s"]
    tol = max(1e-3, 0.01 * cost["serve_device_s"])
    assert abs(req - cost["serve_device_s"]) <= tol
    assert cost["sigs"]
    text = loadgen.format_summary(summary)
    assert "cost:" in text and "matrix serve total" in text


def test_loadgen_summary_has_no_cost_key_with_attr_off(rng):
    cfg = _config(max_queue=64)
    lg = loadgen.LoadgenConfig(mix="random:20", requests=3, warmup=1,
                               mode="closed", concurrency=1, seed=7,
                               verify_gate=1e-4, serve=cfg)
    with SolverServer(cfg) as srv:
        summary = loadgen.run_load(srv, lg)
    assert "cost" not in summary
    assert "cost:" not in loadgen.format_summary(summary)


# -- summarize utilization section ------------------------------------------

def test_summarize_utilization_section(tmp_path, capsys):
    path = tmp_path / "m.jsonl"
    run = {"type": "run_start", "run": "r1", "tool": "t"}
    events = [
        run,
        {"type": "attr_plane", "run": "r1", "event": "start",
         "flops_per_s": 1e9, "bytes_per_s": 1e10, "source": "env"},
        {"type": "attr", "run": "r1", "phase": "serve_batch", "exe": "e",
         "lane": 0, "engine": "blocked", "seconds": 0.5, "requests": 4,
         "flops": 1e8, "compile_s": 0.125},
    ]
    ut = summarize.utilization_summary(events)
    assert ut["observes"] == 1
    assert ut["device_s_total"] == pytest.approx(0.5)
    assert ut["compile_s"] == pytest.approx(0.125)
    assert ut["by_phase"]["serve_batch"]["requests"] == 4
    assert ut["roofline"]["blocked"]["flops_frac"] == pytest.approx(0.2)
    assert ut["peaks"]["source"] == "env"
    # attr-off streams carry no utilization noise
    assert summarize.utilization_summary([run]) == {}
    # the section renders in text and rides the --json document
    with path.open("w") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")
    assert summarize.main([str(path), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["r1"]["utilization"]["observes"] == 1
    assert summarize.main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "utilization (device-time attribution):" in out
    assert "CPU-proxy" in out


# -- ratchet-failure auto-attribution ---------------------------------------

def test_attribute_phases_names_the_guilty_phase():
    prior = {"prepare": 0.1, "slope": 1.0, "verify": 0.2}
    fresh = {"prepare": 0.1, "slope": 2.2, "verify": 0.2}
    text = regress.attribute_phases(fresh, prior, fresh_label="this run",
                                    prior_label="r03")
    assert "biggest regression contributor: slope" in text
    assert "this run" in text and "r03" in text
    # either side missing phases -> None (records predating phases_s)
    assert regress.attribute_phases({}, prior) is None
    assert regress.attribute_phases(fresh, {}) is None


def test_profile_from_phases_adapter_rides_doctor_diff():
    a = doctor.profile_from_phases({"x": 0.5, "y": 0.25}, path="a")
    assert a["profile"]["span_total_s"] == pytest.approx(0.75)
    assert a["profile"]["phases"]["x"] == {"seconds": 0.5, "calls": 1}
    b = doctor.profile_from_phases({"x": 0.9, "y": 0.25}, path="b")
    diff = doctor.diff_profiles(a, b)
    assert diff["phases"][0]["phase"] == "x"  # sorted by delta desc
    assert diff["phases"][0]["delta_s"] == pytest.approx(0.4)
    assert "biggest regression contributor: x" in doctor.format_diff(diff)


# -- profcheck history records ----------------------------------------------

def test_profcheck_history_records_shape():
    from gauss_tpu.obs import profcheck

    summary = {"reconcile": {"throughput_rps": 200.0,
                             "device_s_per_request": 0.002}}
    recs = profcheck.history_records(summary)
    assert ("prof:attr_s_per_request", 0.005, "s") in recs
    assert ("prof:device_s_per_request", 0.002, "s") in recs
    # non-positive / missing values never poison the history
    assert profcheck.history_records({"reconcile": {}}) == []
    assert profcheck.history_records(
        {"reconcile": {"throughput_rps": 0.0}}) == []
