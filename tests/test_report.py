"""Report composer tests (C11 analog; pure text, no device work needed)."""

import json

import pytest

from gauss_tpu.bench import report


def _cells():
    return [
        {"suite": "gauss-internal", "key": "1024", "backend": "tpu",
         "seconds": 0.012, "verified": True, "error": 0.0,
         "reference_s": 1.31},
        {"suite": "gauss-internal", "key": "1024", "backend": "seq",
         "seconds": 0.30, "verified": True, "error": 0.0,
         "reference_s": 1.20},
        {"suite": "gauss-internal", "key": "2048", "backend": "tpu",
         "seconds": 0.045, "verified": True, "error": 0.0,
         "reference_s": 0.509428},
        {"suite": "gauss-internal", "key": "2048", "backend": "seq",
         "seconds": 2.40, "verified": True, "error": 0.0,
         "reference_s": 9.644256},
        # Unverified: must render as FAILED, never a number, and be
        # excluded from speedups/bests.
        {"suite": "gauss-internal", "key": "2048", "backend": "omp",
         "seconds": 0.001, "verified": False, "error": 99.0,
         "reference_s": 0.509428},
        {"suite": "matmul", "key": "2048", "backend": "tpu-pallas",
         "seconds": 0.0011, "verified": True, "error": 1e-6,
         "reference_s": 0.114906},
    ]


def test_report_sections_and_tables():
    text = report.compose_report(_cells(), "t", "hw")
    assert "# t" in text and "**Hardware:** hw" in text
    assert "Gaussian elimination — internal" in text
    assert "Dense matrix multiplication" in text
    # timing table contains the verified numbers
    assert "0.045000" in text and "2.400000" in text
    # speedup vs seq: 2.40/0.045 - 1 = 52.3 -> "+5233%"
    assert "+5233%" in text
    # reference comparison: best engine + margin
    assert "0.509428" in text and "11.3x" in text


def test_report_failed_cells_never_get_numbers():
    text = report.compose_report(_cells(), "t", "hw")
    assert "FAILED" in text
    assert "0.001000" not in text  # the unverified omp time must not appear
    assert "2048/omp" in text      # but the failure is called out


def test_report_best_engine_excludes_unverified():
    # omp at 0.001 s is the fastest number but unverified; best must be tpu.
    text = report.compose_report(_cells(), "t", "hw")
    assert "fastest verified engine is **tpu**" in text


def test_report_profile_sections_included():
    text = report.compose_report(_cells(), "t", "hw",
                                 {"gauss n=64": "phase  seconds\nx  1.0"})
    assert "Profiling of the algorithm" in text
    assert "phase  seconds" in text


def test_report_cli_writes_file(tmp_path):
    src = tmp_path / "cells.json"
    src.write_text(json.dumps(_cells()))
    out = tmp_path / "r" / "REPORT.md"
    rc = report.main([str(src), "--out", str(out), "--title", "CLI report"])
    assert rc == 0
    assert out.read_text().startswith("# CLI report")


def test_report_cli_empty_input_fails(tmp_path):
    src = tmp_path / "cells.json"
    src.write_text("[]")
    assert report.main([str(src)]) == 2


def test_report_profile_runs_real_solve():
    """--profile path: one tiny real solve through the profiler."""
    table = report._profile_gauss(32, "tpu-unblocked")
    assert "computeGauss" in table


def test_scaling_exponent_cubic():
    cells = [{"suite": "s", "key": str(n), "backend": "b",
              "seconds": (n / 256) ** 3, "verified": True, "error": 0.0,
              "reference_s": None} for n in (256, 512, 1024)]
    p, n0, n1 = report._scaling_exponent(cells, "b")
    assert p == pytest.approx(3.0, abs=0.01)
    assert (n0, n1) == (512, 1024)


def test_scaling_exponent_ignores_latency_floor():
    """The fit uses the two largest sizes: a flat small-n latency floor must
    not drag a cubic engine's exponent toward zero."""
    cells = [{"suite": "s", "key": str(n), "backend": "b",
              "seconds": max(1e-4, (n / 2048) ** 3 * 0.002), "verified": True,
              "error": 0.0, "reference_s": None}
             for n in (128, 256, 4096, 8192)]
    p, _, _ = report._scaling_exponent(cells, "b")
    assert p == pytest.approx(3.0, abs=0.01)


def test_scaling_exponent_skips_near_adjacent_sizes():
    """Near-adjacent size pairs (2001 vs 2048 — the padding-edge pair)
    amplify timing noise into absurd exponents (n^33 reached a report
    draft); the fit must skip to a pair >= 1.5x apart, and return None
    when no such pair exists."""
    def cell(n, s):
        return {"suite": "s", "key": str(n), "backend": "b", "seconds": s,
                "verified": True, "error": 0.0, "reference_s": None}

    # 2048/2001 is 1.02x apart: the fit must anchor 2048 against 1024.
    cells = [cell(1024, 0.001), cell(2001, 0.009), cell(2048, 0.008)]
    p, n0, n1 = report._scaling_exponent(cells, "b")
    assert (n0, n1) == (1024, 2048)
    assert p == pytest.approx(3.0, abs=0.01)
    # All sizes near-adjacent: no valid pair, no exponent.
    assert report._scaling_exponent(
        [cell(2001, 0.009), cell(2048, 0.008)], "b") is None


def test_reference_table_excludes_thread_sweep_rows():
    cells = _cells() + [
        {"suite": "gauss-internal", "key": "2048 @16t", "backend": "seq",
         "seconds": 1.5, "verified": True, "error": 0.0,
         "reference_s": 0.509428}]
    text = report.compose_report(cells, "t", "hw")
    ref_section = text.split("Comparison with the reference")[1].split("###")[0]
    assert "@16t" not in ref_section


def test_report_device_span_labeled_separately():
    cells = _cells() + [
        {"suite": "gauss-internal", "key": "2048", "backend": "tpu",
         "seconds": 0.0024, "verified": True, "error": 0.0,
         "reference_s": 0.509428, "span": "device"}]
    text = report.compose_report(cells, "t", "hw")
    assert "tpu [device-span]" in text
    # both the reference-span and device-span tpu numbers appear
    assert "0.045000" in text and "0.002400" in text
    assert "K-chain slope" in text


def test_report_largest_key_ignores_thread_sweep_labels():
    """Inference 'largest size' must be the largest numeric n, not whatever
    key happened to be concatenated last (e.g. '2048 @16t' sweep labels)."""
    cells = _cells() + [
        {"suite": "gauss-internal", "key": "8192", "backend": "tpu",
         "seconds": 0.123, "verified": True, "error": 0.0,
         "reference_s": None, "span": "device"},
        {"suite": "gauss-internal", "key": "2048 @16t", "backend": "threads",
         "seconds": 1.58, "verified": True, "error": 0.0,
         "reference_s": None}]
    text = report.compose_report(cells, "t", "hw")
    assert "At the largest size (8192)" in text


def test_reference_table_folds_sweep_rows_into_base_size():
    """Sweep-only native cells must compete in their base-size row (not be
    hidden), and repeated sizes from merged files must not break the fit."""
    cells = [
        {"suite": "gauss-internal", "key": "4096", "backend": "tpu",
         "seconds": 0.5, "verified": True, "error": 0.0,
         "reference_s": 2.0, "span": "device"},
        {"suite": "gauss-internal", "key": "4096 @16t", "backend": "seq",
         "seconds": 0.1, "verified": True, "error": 0.0, "reference_s": 2.0},
    ]
    text = report.compose_report(cells, "t", "hw")
    ref_section = text.split("Comparison with the reference")[1].split("###")[0]
    assert "0.100000 (seq)" in ref_section and "20.0x" in ref_section


def test_scaling_exponent_tolerates_duplicate_sizes():
    cells = [{"suite": "s", "key": k, "backend": "b", "seconds": s,
              "verified": True, "error": 0.0, "reference_s": None}
             for k, s in (("1024", 0.001), ("2048", 0.004), ("2048", 0.0041))]
    p, _, _ = report._scaling_exponent(cells, "b")
    assert p == pytest.approx(2.0, abs=0.01)


def test_dist_efficiency_table_and_caveat():
    """The gauss-dist section must carry the one-host caveat and a per-engine
    efficiency column computed against the engine's own smallest-shard cell
    (VERDICT round 2 weak #5)."""
    cells = [
        {"suite": "gauss-dist", "key": "1024 @2sh", "backend": "tpu-dist-blocked",
         "seconds": 0.2, "verified": True, "error": 0.0, "reference_s": None,
         "note": "virtual CPU mesh"},
        {"suite": "gauss-dist", "key": "1024 @4sh", "backend": "tpu-dist-blocked",
         "seconds": 0.4, "verified": True, "error": 0.0, "reference_s": None,
         "note": "virtual CPU mesh"},
        {"suite": "gauss-dist", "key": "1024 @8sh", "backend": "tpu-dist-blocked",
         "seconds": 0.8, "verified": True, "error": 0.0, "reference_s": None,
         "note": "virtual CPU mesh"},
    ]
    text = report.compose_report(cells, "t", "hw")
    assert "Shard-sweep efficiency" in text
    assert "NOT an ICI scaling measurement" in text
    # eff at 4 shards: 0.2*2/(0.4*4) = 25%; at 8: 0.2*2/(0.8*8) = 6%.
    assert "(25% eff)" in text and "(6% eff)" in text
    assert "0.200000 (base)" in text


def test_precision_suite_renders_notes():
    cells = [
        {"suite": "gauss-precision", "key": "8192", "backend": "tpu[highest]",
         "seconds": 0.058, "verified": True, "error": 1e-7,
         "reference_s": None, "span": "device",
         "note": "gemm_precision=highest, ds-refine x3, K=(1,2); 6.3 TF/s useful"},
        {"suite": "gauss-precision", "key": "8192", "backend": "tpu[high]",
         "seconds": 0.030, "verified": True, "error": 2e-7,
         "reference_s": None, "span": "device",
         "note": "gemm_precision=high, ds-refine x3, K=(1,2); 12.2 TF/s useful"},
    ]
    text = report.compose_report(cells, "t", "hw")
    assert "GEMM precision sweep" in text
    assert "6.3 TF/s useful" in text and "12.2 TF/s useful" in text


def test_failed_cells_show_cause():
    """A FAILED cell's note (the captured exception) must surface in the
    report, not just the JSON (VERDICT round 2 weak #2)."""
    cells = [
        {"suite": "gauss-external", "key": "memplus", "backend": "tpu",
         "seconds": 0.0, "verified": False, "error": float("nan"),
         "reference_s": None, "span": "device",
         "note": "failed: XlaRuntimeError: compile timed out"}]
    text = report.compose_report(cells, "t", "hw")
    assert "memplus/tpu [device-span] — failed: XlaRuntimeError" in text
