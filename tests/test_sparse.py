"""gauss_tpu.sparse: CSR assembly, SpMV kernels, Krylov solvers,
preconditioners, routing/recovery integration, and the duplicate-semantics
and density-boundary contracts the ISSUE pins.

The detector boundary tests assert the sparse/dense threshold EXACTLY —
density == SPARSE_MAX_DENSITY classifies sparse, one entry more does not,
n == SPARSE_MIN_N - 1 never does — and that the coordinate-stream
classifier agrees with the dense-scan classifier byte for byte at the
boundary. The datfile tests pin the three duplicate conventions side by
side: strict rejects, non-strict densify is last-wins (fscanf parity),
non-strict sparse assembly sums.
"""

import io
import json

import numpy as np
import pytest

from gauss_tpu.io import datfile, synthetic
from gauss_tpu.sparse import (
    CsrMatrix,
    IterativeStagnationError,
    build_preconditioner,
    solve_bicgstab,
    solve_cg,
    solve_gmres,
    solve_sparse,
    spmv_coo,
    spmv_ell,
    spmv_ell_pallas,
)
from gauss_tpu.sparse.precond import PRECOND_KINDS, apply_precond
from gauss_tpu.structure.cholesky import NotSPDError
from gauss_tpu.structure.detect import (
    SPARSE_MAX_DENSITY,
    SPARSE_MIN_N,
    StructureMismatchError,
    detect_structure,
    detect_structure_coords,
)

GATE = 1e-4


def _system(n=200, nnz_per_row=6, seed=1, symmetric=True):
    rows, cols, vals = synthetic.sparse_coords(
        n, nnz_per_row, seed=seed, symmetric=symmetric)
    a = CsrMatrix.from_coords(n, rows, cols, vals)
    rng = np.random.default_rng(np.random.SeedSequence((seed, n, 7)))
    return a, rng.standard_normal(n)


# -- CSR assembly ----------------------------------------------------------

class TestCsrMatrix:
    def test_duplicates_are_summed(self):
        a = CsrMatrix.from_coords(
            3, [0, 0, 1, 2, 0], [0, 0, 1, 2, 2], [1.0, 2.5, 4.0, 5.0, -1.0])
        dense = a.to_dense()
        assert dense[0, 0] == 3.5 and dense[0, 2] == -1.0
        assert a.nnz == 4  # the duplicate pair collapsed to one entry

    def test_exact_zeros_dropped_by_default(self):
        a = CsrMatrix.from_coords(2, [0, 1], [1, 0], [0.0, 2.0])
        assert a.nnz == 1
        kept = CsrMatrix.from_coords(2, [0, 1], [1, 0], [0.0, 2.0],
                                     drop_zeros=False)
        assert kept.nnz == 2

    def test_cancelling_duplicates_drop(self):
        a = CsrMatrix.from_coords(2, [0, 0], [1, 1], [3.0, -3.0])
        assert a.nnz == 0

    def test_out_of_range_rejected(self):
        with pytest.raises(ValueError):
            CsrMatrix.from_coords(2, [0, 2], [0, 0], [1.0, 1.0])
        with pytest.raises(ValueError):
            CsrMatrix.from_coords(2, [0, -1], [0, 0], [1.0, 1.0])

    def test_from_dense_round_trip(self):
        rng = np.random.default_rng(3)
        d = np.where(rng.random((40, 40)) < 0.1, rng.standard_normal((40, 40)), 0.0)
        a = CsrMatrix.from_dense(d)
        assert np.array_equal(a.to_dense(), d)
        assert a.nnz == int((d != 0).sum())

    def test_densify_limit_refuses(self):
        a, _ = _system(n=64)
        big = CsrMatrix(n=10_000, indptr=np.zeros(10_001, np.int64),
                        indices=np.zeros(0, np.int32),
                        data=np.zeros(0, np.float64))
        with pytest.raises(ValueError, match="densif"):
            big.to_dense()
        assert a.to_dense().shape == (64, 64)  # under the limit: fine

    def test_gershgorin_certificate(self):
        a, _ = _system(symmetric=True)
        assert a.is_symmetric() and a.gershgorin_spd()
        g, _ = _system(symmetric=False)
        assert not g.gershgorin_spd()

    def test_ell_and_coo_match_dense_matvec(self):
        a, b = _system(n=150, nnz_per_row=5)
        dense = a.to_dense()
        np.testing.assert_allclose(a.matvec(b), dense @ b, rtol=1e-12)
        cols, vals = a.ell()
        assert cols.shape == vals.shape == (150, a.max_row_nnz)
        np.testing.assert_allclose(
            np.asarray(spmv_ell(cols, vals, b)), dense @ b, rtol=1e-5)
        rows, ccols, cvals = a.coo()
        np.testing.assert_allclose(
            np.asarray(spmv_coo(rows, ccols, cvals, b, n=150)),
            dense @ b, rtol=1e-5)

    def test_pallas_spmv_matches(self):
        a, b = _system(n=130, nnz_per_row=5)
        cols, vals = a.ell()
        got = np.asarray(spmv_ell_pallas(cols, vals, b, bm=32))
        np.testing.assert_allclose(got, a.to_dense() @ b, rtol=1e-5)


# -- streaming .dat reader + duplicate semantics ---------------------------

class TestDatStreaming:
    def _text(self, n=120, nnz_per_row=5, seed=4):
        rows, cols, vals = synthetic.sparse_coords(n, nnz_per_row, seed=seed)
        buf = io.StringIO()
        datfile.write_dat(buf, n=n, rows=rows, cols=cols, vals=vals)
        return buf.getvalue(), (rows, cols, vals)

    def test_iter_coords_round_trip_exact(self):
        text, (rows, cols, vals) = self._text()
        st = datfile.iter_coords(io.StringIO(text), strict=True, chunk=37)
        assert st.n == 120 and st.declared_nnz == len(vals)
        got_r, got_c, got_v = [], [], []
        nchunks = 0
        for r, c, v in st:
            assert len(r) <= 37
            got_r.append(r), got_c.append(c), got_v.append(v)
            nchunks += 1
        assert nchunks > 1  # actually chunked
        # %.17g round trip is EXACT, not approximately equal
        assert np.array_equal(np.concatenate(got_r), rows)
        assert np.array_equal(np.concatenate(got_c), cols)
        assert np.array_equal(np.concatenate(got_v), vals)

    def test_from_dat_matches_read_dat_densify(self):
        text, _ = self._text()
        a = CsrMatrix.from_dat(io.StringIO(text), strict=True)
        n, rows, cols, vals = datfile.read_dat(io.StringIO(text))
        assert np.array_equal(a.to_dense(),
                              datfile.densify(n, rows, cols, vals))

    def test_duplicate_three_conventions(self):
        dup = "2 2 3\n1 1 1.5\n1 1 2.5\n2 2 1\n0 0 0\n"
        # strict: typed rejection, naming both lines
        with pytest.raises(datfile.DatFormatError, match="duplicate"):
            for _ in datfile.iter_coords(io.StringIO(dup), strict=True):
                pass
        with pytest.raises(datfile.DatFormatError, match="line 2"):
            datfile.read_dat(io.StringIO(dup), strict=True)
        # non-strict densify: fscanf last-wins parity
        n, r, c, v = datfile.read_dat(io.StringIO(dup), strict=False)
        assert datfile.densify(n, r, c, v)[0, 0] == 2.5
        # non-strict sparse assembly: summed
        a = CsrMatrix.from_dat(io.StringIO(dup), strict=False)
        assert a.to_dense()[0, 0] == 4.0

    def test_stream_validation(self):
        with pytest.raises(datfile.DatFormatError, match="promised"):
            for _ in datfile.iter_coords(io.StringIO("2 2 2\n1 1 1\n")):
                pass
        with pytest.raises(datfile.DatFormatError, match="terminator"):
            for _ in datfile.iter_coords(
                    io.StringIO("1 1 1\n1 1 2\n"), strict=True):
                pass
        # EOF-terminated is fine non-strict
        st = datfile.iter_coords(io.StringIO("1 1 1\n1 1 2\n"), strict=False)
        (r, c, v), = list(st)
        assert v[0] == 2.0
        with pytest.raises(datfile.DatFormatError, match="out of bounds"):
            for _ in datfile.iter_coords(io.StringIO("2 2 1\n3 1 1\n0 0 0\n")):
                pass
        with pytest.raises(datfile.DatFormatError, match="header"):
            datfile.iter_coords(io.StringIO("2 3 1\n"))

    def test_single_pass(self):
        text, _ = self._text()
        st = datfile.iter_coords(io.StringIO(text), strict=False)
        list(st)
        with pytest.raises(RuntimeError, match="single-pass"):
            iter(st)


# -- detector density boundary ---------------------------------------------

class TestSparseBoundary:
    def _boundary_coords(self, n, nnz):
        """Exactly ``nnz`` entries: the diagonal plus symmetric off-diagonal
        pairs far from the diagonal (so bandwidth stays > n // 8 and the
        banded/blockdiag classes cannot win)."""
        rows = list(range(n))
        cols = list(range(n))
        vals = [float(n)] * n
        k = nnz - n
        assert k >= 0 and k % 2 == 0
        pairs = 0
        for i in range(n):
            for j in range(i + n // 2, n):
                if pairs * 2 >= k:
                    break
                rows += [i, j]
                cols += [j, i]
                vals += [-1.0, -1.0]
                pairs += 1
            if pairs * 2 >= k:
                break
        return (np.array(rows), np.array(cols), np.array(vals))

    def test_density_threshold_exact(self):
        n = 256
        at = int(SPARSE_MAX_DENSITY * n * n)  # nnz AT the threshold
        rows, cols, vals = self._boundary_coords(n, at)
        info = detect_structure_coords(n, rows, cols, vals)
        assert info.density == SPARSE_MAX_DENSITY
        assert info.kind == "sparse"
        # one entry past the threshold: no longer sparse
        rows2, cols2, vals2 = self._boundary_coords(n, at + 2)
        info2 = detect_structure_coords(n, rows2, cols2, vals2)
        assert info2.density > SPARSE_MAX_DENSITY
        assert info2.kind != "sparse"

    def test_min_n_floor(self):
        n = SPARSE_MIN_N - 1
        rows, cols, vals = self._boundary_coords(n, n + 2)
        info = detect_structure_coords(n, rows, cols, vals)
        assert info.density < SPARSE_MAX_DENSITY
        assert info.kind != "sparse"  # small systems stay on dense engines

    def test_coords_and_dense_classifiers_agree_at_boundary(self):
        n = 256
        for nnz in (int(SPARSE_MAX_DENSITY * n * n),
                    int(SPARSE_MAX_DENSITY * n * n) + 2):
            rows, cols, vals = self._boundary_coords(n, nnz)
            ci = detect_structure_coords(n, rows, cols, vals)
            di = detect_structure(datfile.densify(n, rows, cols, vals))
            assert ci == di  # byte-for-byte StructureInfo equality
            assert ci.kind == di.kind

    def test_exact_structure_beats_sparse(self):
        # A sparse-density banded matrix still routes banded: the O(n b^2)
        # direct factor beats iteration.
        a = synthetic.banded_matrix(512, 1)
        info = detect_structure(a)
        assert info.density <= SPARSE_MAX_DENSITY
        assert info.kind == "banded"


# -- Krylov solvers --------------------------------------------------------

class TestKrylov:
    def test_all_methods_converge_certified(self):
        a, b = _system(n=220)
        dense = a.to_dense()
        for fn in (solve_cg, solve_gmres, solve_bicgstab):
            res = fn(a, b, tol=GATE)
            assert res.converged and res.rel_residual <= GATE
            rel = np.linalg.norm(dense @ res.x - b) / np.linalg.norm(b)
            assert rel <= GATE
            assert res.iterations > 0
            assert len(res.residuals) >= 1
            assert np.isfinite(res.residuals).all()

    def test_cg_refuses_uncertified(self):
        a, b = _system(symmetric=False)
        with pytest.raises(NotSPDError):
            solve_cg(a, b)

    def test_gmres_bicgstab_handle_nonsymmetric(self):
        a, b = _system(n=220, symmetric=False)
        dense = a.to_dense()
        for fn in (solve_gmres, solve_bicgstab):
            res = fn(a, b, tol=GATE)
            rel = np.linalg.norm(dense @ res.x - b) / np.linalg.norm(b)
            assert res.converged and rel <= GATE

    def test_stagnation_is_typed_and_carries_result(self):
        a, b = _system(n=220)
        with pytest.raises(IterativeStagnationError) as ei:
            solve_cg(a, b, tol=1e-30, maxiter=3)
        err = ei.value
        assert err.method == "cg" and err.iterations == 3
        assert err.result is not None and err.result.x.shape == b.shape
        # raise_on_stagnation=False returns the partial result instead
        res = solve_cg(a, b, tol=1e-30, maxiter=3,
                       raise_on_stagnation=False)
        assert not res.converged

    def test_multiple_rhs(self):
        a, _ = _system(n=180)
        rng = np.random.default_rng(9)
        B = rng.standard_normal((180, 3))
        res = solve_cg(a, B, tol=GATE)
        r = a.to_dense() @ res.x - B
        assert (np.linalg.norm(r, axis=0)
                <= GATE * np.linalg.norm(B, axis=0)).all()


# -- preconditioners -------------------------------------------------------

class TestPreconditioners:
    def test_each_kind_converges(self):
        a, b = _system(n=240)
        dense = a.to_dense()
        for kind in PRECOND_KINDS:
            prec = build_preconditioner(a, kind) if kind != "none" else None
            res = solve_cg(a, b, precond=prec, tol=GATE)
            rel = np.linalg.norm(dense @ res.x - b) / np.linalg.norm(b)
            assert res.converged and rel <= GATE, kind

    def test_apply_is_jit_consistent(self):
        a, b = _system(n=96)
        for kind in ("jacobi", "block_jacobi", "tridiag", "ilu0"):
            prec = build_preconditioner(a, kind, block=16)
            out = np.asarray(apply_precond(prec, b))
            assert out.shape == b.shape and np.isfinite(out).all()

    def test_ic0_requires_certificate(self):
        g, _ = _system(symmetric=False)
        with pytest.raises(StructureMismatchError):
            build_preconditioner(g, "ic0")

    def test_unknown_kind_rejected(self):
        a, _ = _system(n=64)
        with pytest.raises(ValueError):
            build_preconditioner(a, "spai")


# -- solve_sparse front door + obs -----------------------------------------

class TestSolveSparse:
    def test_auto_certified_uses_cg(self, tmp_path):
        from gauss_tpu import obs
        from gauss_tpu.obs import registry

        a, b = _system(n=260)
        out = tmp_path / "sparse.jsonl"
        with obs.run(metrics_out=str(out)):
            res = solve_sparse(a, b)
        assert res.method == "cg" and res.converged
        events = registry.read_events(str(out))
        evs = [e for e in events if e.get("type") == "sparse_solve"]
        assert len(evs) == 1
        ev = evs[0]
        assert ev["method"] == "cg" and ev["converged"]
        assert ev["certified_spd"] and ev["n"] == 260
        assert isinstance(ev["residuals"], list)

    def test_auto_uncertified_skips_cg(self):
        a, b = _system(n=260, symmetric=False)
        res = solve_sparse(a, b)
        assert res.method in ("gmres", "bicgstab") and res.converged

    def test_dense_input_accepted(self):
        a, b = _system(n=128)
        res = solve_sparse(a.to_dense(), b)
        assert res.converged

    def test_summary_and_regress_ingest(self, tmp_path):
        from gauss_tpu.obs import regress, summarize
        from gauss_tpu.sparse.check import history_records

        summary = {
            "kind": "sparse_solve", "gate": GATE,
            "methods": {"cg": {"s_per_solve": 0.01, "iterations": 7}},
            "giant": {"s_per_solve": 1.5, "peak_rss_bytes": 4.5e8},
        }
        recs = dict(
            ((m, u), v) for m, v, u in history_records(summary))
        assert recs[("sparse:cg/s_per_solve", "s")] == 0.01
        assert recs[("sparse:giant/peak_rss_bytes", "bytes")] == 4.5e8
        p = tmp_path / "summary.json"
        p.write_text(json.dumps(summary))
        ingested = regress.ingest_file(str(p))
        assert {r["metric"] for r in ingested} == {
            "sparse:cg/s_per_solve", "sparse:cg/iterations",
            "sparse:giant/s_per_solve", "sparse:giant/peak_rss_bytes"}
        assert all(r["kind"] == "sparse" for r in ingested)
        # the summarize section folds sparse_solve events
        evs = [{"run": "r1", "type": "run_start"},
               {"run": "r1", "type": "sparse_solve", "method": "cg",
                "precond": "jacobi", "converged": True, "iterations": 7,
                "certified_spd": True, "n": 100, "nnz": 500,
                "rel_residual": 5e-5, "wall_s": 0.01}]
        sp = summarize.sparse_summary(evs)
        assert sp["methods"]["cg"]["converged"] == 1
        assert "sparse (Krylov) solves:" in summarize.summarize_run(evs, "r1")


# -- routing + recovery integration ----------------------------------------

class TestRoutingIntegration:
    def test_solve_auto_routes_sparse(self):
        from gauss_tpu.structure import solve_auto

        a, b = _system(n=300)
        res = solve_auto(a.to_dense(), b, gate=GATE)
        assert res.rung == "cg" and res.rung_index == 0
        rel = np.linalg.norm(a.to_dense() @ res.x - b) / np.linalg.norm(b)
        assert rel <= GATE

    def test_uncertified_demotes_typed_to_gmres(self):
        from gauss_tpu.structure import solve_auto

        a, b = _system(n=300, symmetric=False)
        res = solve_auto(a.to_dense(), b, gate=GATE)
        assert res.rung == "gmres"
        assert ("cg", "exception:NotSPDError") in [
            tuple(e) for e in res.escalations]

    def test_structured_rungs_sparse_head(self):
        from gauss_tpu.resilience import recover

        rungs = recover.structured_rungs("sparse")
        assert rungs[:3] == ("cg", "gmres", "bicgstab")
        assert "blocked" in rungs  # the dense chain still backstops

    def test_loadgen_sparse_token(self):
        from gauss_tpu.serve.loadgen import materialize, parse_mix

        (spec, w), = parse_mix("sparse:300/6")
        assert spec.kind == "sparse"
        a, b = materialize(spec, np.random.default_rng(0))
        info = detect_structure(a)
        assert info.kind == "sparse"
        for bad in ("sparse:0", "sparse:8192", "sparse:64/0"):
            with pytest.raises(ValueError):
                parse_mix(bad)

    def test_matrix_gen_sparse_writes_coords(self, capsys):
        from gauss_tpu.cli.matrix_gen import main

        assert main(["90", "--structure", "sparse:5", "--python"]) == 0
        text = capsys.readouterr().out
        a = CsrMatrix.from_dat(io.StringIO(text), strict=True)
        rows, cols, vals = synthetic.sparse_coords(90, nnz_per_row=5)
        dense = np.zeros((90, 90))
        dense[rows, cols] = vals
        assert np.array_equal(a.to_dense(), dense)
        assert main(["10", "--structure", "sparse:0", "--python"]) == 1


# -- generator determinism --------------------------------------------------

class TestSyntheticSparse:
    def test_deterministic_and_dominant(self):
        r1 = synthetic.sparse_coords(500, 8, seed=11)
        r2 = synthetic.sparse_coords(500, 8, seed=11)
        for x, y in zip(r1, r2):
            assert np.array_equal(x, y)
        a = CsrMatrix.from_coords(500, *r1)
        assert a.gershgorin_spd()
        assert a.nnz <= 500 * 8 + 500

    def test_nonsymmetric_still_dominant(self):
        rows, cols, vals = synthetic.sparse_coords(200, 8, seed=2,
                                                   symmetric=False)
        a = CsrMatrix.from_coords(200, rows, cols, vals)
        assert not a.is_symmetric()
        d = np.abs(a.diagonal())
        off = np.zeros(200)
        rr = a.row_ids()
        mask = rr != a.indices
        np.add.at(off, rr[mask], np.abs(a.data[mask]))
        assert (d > off).all()  # invertible by dominance

    def test_sparse_matrix_densify_cap(self):
        with pytest.raises(ValueError, match="densifies"):
            synthetic.sparse_matrix(5000)
