"""gauss_tpu.outofcore — the host-streamed engine (ISSUE 13).

Covers: numerical identity with the in-core chunked factor (the shared
_factor_group contract), the 1e-4 solve gate, streaming boundedness (the
device-byte ledger), the transfer/compute span accounting, window sizing
+ admission, handoff routing (dtype-aware), checkpoint resume, the ABFT
rider, the recovery rung, the serve lane, and the regress/bench plumbing.
"""

import json
import os

import numpy as np
import pytest

from gauss_tpu import obs, outofcore
from gauss_tpu.outofcore import stream as ooc_stream


@pytest.fixture
def rng():
    return np.random.default_rng(1349)


def _system(rng, n, k=None):
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal(n if k is None else (n, k))
    return a, b


def test_factor_bit_identical_to_chunked(rng):
    """The streamed factor IS the in-core chunked factor: same shared
    per-group step, same trailing math — bit-identical m/perm/linv/uinv
    on the CPU proxy (column-tiled trailing GEMMs do not change
    per-element reduction order)."""
    import jax.numpy as jnp

    from gauss_tpu.core import blocked

    n = 384
    a, _ = _system(rng, n)
    fac = outofcore.lu_factor_outofcore(a, panel=64, chunk=2, ct=128)
    ref = blocked.lu_factor_blocked_chunked(jnp.asarray(a, jnp.float32),
                                            panel=64, chunk=2)
    assert np.array_equal(fac.perm, np.asarray(ref.perm))
    assert np.array_equal(fac.m, np.asarray(ref.m))
    assert np.array_equal(fac.linv, np.asarray(ref.linv))
    assert np.array_equal(fac.uinv, np.asarray(ref.uinv))
    assert fac.min_abs_pivot == pytest.approx(
        float(ref.min_abs_pivot), rel=0)


def test_solve_gate_and_stream_stats(rng):
    """The refined streamed solve lands far under the 1e-4 gate, and the
    StreamStats accounting is coherent: the trailing region was tiled,
    the full matrix was streamed at least once, and the measured device
    ledger peak stays under half the in-core working set."""
    n = 256
    a, b = _system(rng, n)
    x = outofcore.solve_outofcore(a, b, panel=64, chunk=1, ct=64)
    rel = np.linalg.norm(a @ x - b) / np.linalg.norm(b)
    assert rel < 1e-8
    s = outofcore.last_stream_stats()
    assert s.tiles >= 2 and s.groups == 4 and s.solves >= 2
    assert s.bytes_h2d >= n * n * 4          # the matrix went down at least once
    assert s.bytes_d2h >= n * n * 4          # ... and came back
    assert 0 < s.peak_device_bytes < 0.5 * 3 * n * n * 4
    assert s.live_device_bytes == 0          # every buffer accounted + dropped
    assert 0.0 <= s.overlap_fraction <= 1.0
    assert s.stall_fraction == pytest.approx(1.0 - s.overlap_fraction)


def test_transfer_spans_recorded(rng):
    """The obs stream carries the per-tile transfer/stall spans (what
    obs.doctor attributes stream-vs-compute time from) plus the final
    outofcore accounting event."""
    n = 192
    a, b = _system(rng, n)
    with obs.run() as rec:
        outofcore.solve_outofcore(a, b, panel=64, chunk=1, ct=64, iters=1)
    spans = [e["name"] for e in rec.events if e["type"] == "span"]
    for name in ("outofcore.h2d", "outofcore.d2h", "outofcore.compute_wait"):
        assert name in spans, f"missing span {name}"
    oev = [e for e in rec.events if e["type"] == "outofcore"]
    assert any(e.get("event") == "solve_complete" for e in oev)
    done = [e for e in oev if e.get("event") == "solve_complete"][0]
    assert done["peak_device_bytes"] > 0 and done["tiles"] >= 2


def test_multi_rhs(rng):
    n, k = 192, 3
    a, b = _system(rng, n, k)
    x = outofcore.solve_outofcore(a, b, panel=64, chunk=1, ct=64)
    assert x.shape == (n, k)
    np.testing.assert_allclose(x, np.linalg.solve(a, b), rtol=1e-8,
                               atol=1e-8)


def test_window_sizing_and_tuned_consult(monkeypatch):
    """outofcore_window sizes ct from the budget fraction (panel-multiple,
    window + group block within OUTOFCORE_DEVICE_FRAC of the budget), and
    a tuned store short-circuits it (op outofcore, axis ct)."""
    from gauss_tpu.tune import apply as tapply

    n, panel, chunk = 4096, 128, 4
    budget = 64 * 2**20
    ct = outofcore.outofcore_window(n, panel, chunk, itemsize=4,
                                    budget=budget)
    assert ct % panel == 0 and ct >= panel
    workset = n * (chunk * panel + ooc_stream.PIPELINE_TILE_BUFFERS * ct) * 4
    assert workset <= outofcore.OUTOFCORE_DEVICE_FRAC * budget

    monkeypatch.setattr(tapply, "override",
                        lambda op, n_, name, **kw: 512
                        if (op, name) == ("outofcore", "ct") else None)
    assert outofcore.outofcore_window(n, panel, chunk) == 512


def test_admission(monkeypatch):
    """outofcore_fits: host-side admission against OS RAM, device-side
    against the budget fraction — the typed-no is the routing error's
    last line of defense."""
    assert outofcore.outofcore_fits(512)
    monkeypatch.setattr(ooc_stream, "host_memory_budget", lambda: 10**6)
    assert not outofcore.outofcore_fits(4096)
    monkeypatch.undo()
    assert not outofcore.outofcore_fits(1 << 20, budget=10**6)


def test_handoff_dtype_aware_routing(rng):
    """ISSUE 13 satellite: itemsize derives from the requested dtype — a
    bf16 request near the budget routes single-chip where f32 would not,
    and the route event carries the itemsize it was sized with."""
    import jax.numpy as jnp

    from gauss_tpu.core import blocked
    from gauss_tpu.dist.mesh import make_mesh

    n = 64
    a, b = _system(rng, n)
    budget = 3 * n * n * 3  # between the bf16 (2-byte) and f32 working sets
    with obs.run() as rec:
        blocked.solve_handoff(a, b, budget=budget, mesh=make_mesh(1),
                              dtype=jnp.bfloat16, iters=6)
    routes = [e for e in rec.events if e["type"] == "route"]
    assert routes[-1]["lane"] == "single_chip"
    assert routes[-1]["itemsize"] == 2
    assert routes[-1]["est_bytes"] == 3 * n * n * 2

    with obs.run() as rec:
        blocked.solve_handoff(a, b, budget=budget, mesh=make_mesh(1))
    routes = [e for e in rec.events if e["type"] == "route"]
    assert routes[-1]["lane"] == "outofcore"      # f32 est busts the budget
    assert routes[-1]["itemsize"] == 4

    # An already-lowered OPERAND keeps its own itemsize too.
    a32 = a.astype(np.float32)
    with obs.run() as rec:
        blocked.solve_handoff(a32, b.astype(np.float32),
                              budget=3 * n * n * 4, mesh=make_mesh(1))
    assert [e for e in rec.events
            if e["type"] == "route"][-1]["itemsize"] == 4


def test_handoff_engine_param(rng):
    from gauss_tpu.core import blocked

    n = 96
    a, b = _system(rng, n)
    x = blocked.solve_handoff(a, b, engine="outofcore")
    np.testing.assert_allclose(x, np.linalg.solve(a, b), rtol=1e-8,
                               atol=1e-8)
    with pytest.raises(ValueError, match="unknown handoff engine"):
        blocked.solve_handoff(a, b, engine="warp")
    with pytest.raises(ValueError, match="do not apply"):
        blocked.solve_handoff(a, b, engine="outofcore", unroll=True)


def test_checkpoint_resume_bit_identical(rng, tmp_path):
    """A streamed factorization killed between groups resumes from the
    checkpoint.py-idiom carry and finishes BIT-IDENTICAL to an
    uninterrupted run; the checkpoint files are cleaned on success."""
    n = 256
    a, _ = _system(rng, n)
    full = outofcore.lu_factor_outofcore(a, panel=64, chunk=1, ct=64)
    ck = tmp_path / "giant.ckpt"

    orig = ooc_stream._group_step
    calls = {"n": 0}

    def preempt(*args, **kw):
        calls["n"] += 1
        if calls["n"] > 2:
            raise RuntimeError("preempted")
        return orig(*args, **kw)

    ooc_stream._group_step = preempt
    try:
        with pytest.raises(RuntimeError, match="preempted"):
            outofcore.lu_factor_outofcore(a, panel=64, chunk=1, ct=64,
                                          checkpoint_path=ck)
    finally:
        ooc_stream._group_step = orig
    assert ck.exists()
    fac = outofcore.lu_factor_outofcore(a, panel=64, chunk=1, ct=64,
                                        checkpoint_path=ck)
    assert np.array_equal(fac.m, full.m)
    assert np.array_equal(fac.perm, full.perm)
    assert np.array_equal(fac.linv, full.linv)
    assert not ck.exists()


def test_checkpoint_mismatch_typed(rng, tmp_path):
    """A checkpoint from a DIFFERENT operand is a typed mismatch, never a
    silently wrong factor (the checkpoint.py digest contract, inherited)."""
    from gauss_tpu.resilience.checkpoint import CheckpointMismatchError

    n = 128
    a, _ = _system(rng, n)
    ck = tmp_path / "ooc.ckpt"
    outofcore.lu_factor_outofcore(a, panel=64, chunk=1, ct=64,
                                  checkpoint_path=ck, keep=True)
    assert ck.exists()  # keep=True leaves the last intermediate carry
    a2 = a + 1.0
    with pytest.raises(CheckpointMismatchError):
        outofcore.lu_factor_outofcore(a2, panel=64, chunk=1, ct=64,
                                      checkpoint_path=ck)


def test_abft_clean_run(rng):
    n = 256
    a, _ = _system(rng, n)
    fac = outofcore.lu_factor_outofcore(a, panel=64, chunk=1, ct=64,
                                        abft=True)
    assert fac.abft_err is not None and fac.abft_err.shape == (4,)
    from gauss_tpu.resilience.abft import default_tol

    assert fac.abft_err.max() < default_tol(256, np.float32,
                                            float(np.abs(a).max()))


def test_abft_detects_tile_corruption(rng):
    """A corrupted trailing tile (inject site outofcore.tile) trips the
    per-tile checksum identity: typed SDCDetectedError, localized to the
    group that produced it."""
    from gauss_tpu.resilience import inject

    n = 256
    a, _ = _system(rng, n)
    plan = inject.FaultPlan.parse("outofcore.tile=nan:seed=7")
    inject.install(plan)
    try:
        with pytest.raises(outofcore.SDCDetectedError) as ei:
            outofcore.lu_factor_outofcore(a, panel=64, chunk=1, ct=64,
                                          abft=True)
    finally:
        inject.uninstall()
    assert ei.value.group >= 0 and ei.value.err > 0


def test_recover_rung(rng):
    from gauss_tpu.resilience import recover

    n = 96
    a, b = _system(rng, n)
    rr = recover.solve_resilient(a, b, rungs=("outofcore", "numpy_f64"))
    assert rr.rung == "outofcore" and rr.rung_index == 0
    np.testing.assert_allclose(rr.x, np.linalg.solve(a, b), rtol=1e-8,
                               atol=1e-8)


def test_serve_outofcore_lane(rng):
    """ServeConfig(outofcore_handoff=True, device_budget=tiny): an
    oversized handoff request streams (lane=outofcore) and verifies."""
    from gauss_tpu.serve.admission import ServeConfig
    from gauss_tpu.serve.server import SolverServer

    n = 96
    a, b = _system(rng, n)
    srv = SolverServer(ServeConfig(ladder=(16, 32), outofcore_handoff=True,
                                   device_budget=1024, verify_gate=1e-4))
    srv.start()
    try:
        res = srv.submit(a, b).result(timeout=120)
    finally:
        srv.stop()
    assert res.ok and res.lane == "outofcore"
    np.testing.assert_allclose(res.x, np.linalg.solve(a, b), rtol=1e-6,
                               atol=1e-6)


def test_bench_summary_ingest(tmp_path):
    """kind=outofcore_bench summaries regress-ingest into the streamed
    metrics (single source: check.history_records)."""
    from gauss_tpu.obs import regress

    summary = {"kind": "outofcore_bench",
               "smoke": {"n": 2048, "s_per_solve": 4.4,
                         "stall_fraction": 0.13,
                         "peak_device_frac": 0.33},
               "giant": {"n": 32768, "s_per_solve": 400.0}}
    p = tmp_path / "ooc.json"
    p.write_text(json.dumps(summary))
    recs = regress.ingest_file(p)
    by = {r["metric"]: r["value"] for r in recs}
    assert by["outofcore:s_per_solve"] == 4.4
    assert by["outofcore:stall_fraction"] == 0.13
    assert by["outofcore:peak_device_frac"] == 0.33
    assert by["outofcore:n32768/s_per_solve"] == 400.0
    assert all(r["kind"] == "outofcore" for r in recs)


def test_committed_history_epochs():
    """The repo ships >= 3 seeded outofcore_bench epochs, so the gate's
    --regress-check has baselines from this PR on."""
    hist = os.path.join(os.path.dirname(__file__), os.pardir, "reports",
                        "history.jsonl")
    metrics = []
    with open(hist) as f:
        for line in f:
            line = line.strip()
            if line:
                rec = json.loads(line)
                if rec.get("kind") == "outofcore":
                    metrics.append(rec["metric"])
    assert metrics.count("outofcore:s_per_solve") >= 3
    assert metrics.count("outofcore:stall_fraction") >= 3


def test_tune_space_axes():
    from gauss_tpu.tune import space

    axes = {ax.name: ax for ax in space.space_for("outofcore")}
    assert axes["ct"].seed == space.OUTOFCORE_CT_SEED
    assert axes["chunk"].seed == space.OUTOFCORE_CHUNK_SEED
    assert not axes["device_frac"].sweep_default
    from gauss_tpu.tune.runner import _MEASURERS

    assert "outofcore" in _MEASURERS


def test_check_cli_smoke(tmp_path):
    """The gate CLI end to end at micro sizes: verifies, asserts
    boundedness + routing, writes the regress-ingestable summary."""
    from gauss_tpu.outofcore import check

    metrics = tmp_path / "ooc.jsonl"
    summary = tmp_path / "summary.json"
    rc = check.main(["--n", "256", "--panel", "64", "--ct", "64",
                     "--chunk", "1", "--routing-n", "96", "--seed", "7",
                     "--metrics-out", str(metrics),
                     "--summary-json", str(summary)])
    assert rc == 0
    doc = json.loads(summary.read_text())
    assert doc["kind"] == "outofcore_bench" and doc["ok"]
    assert doc["smoke"]["verified"] and doc["smoke"]["streamed"]
    assert doc["routing"]["verified"]
