"""Resilience-layer tests: the fault-injection framework (determinism,
hook-point plumbing through core/serve/dist), the recovery ladder (gating,
escalation order, typed exhaustion), panel-granular checkpoint/resume
(bit-identity after a kill), the chaos campaign runner, and the
summarize/regress integration.

All CPU (conftest pins the platform); sizes stay small — these tests are
about fault PATHS, not FLOPs.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from gauss_tpu import obs
from gauss_tpu.core import blocked
from gauss_tpu.obs import regress, summarize
from gauss_tpu.resilience import checkpoint as ckpt
from gauss_tpu.resilience import chaos, inject, recover
from gauss_tpu.verify import checks

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _system(rng, n, k=None):
    a = rng.standard_normal((n, n))
    a[np.arange(n), np.arange(n)] += float(n)
    b = rng.standard_normal(n) if k is None else rng.standard_normal((n, k))
    return a, b


# -- inject: plan parsing + deterministic triggering -----------------------

def test_fault_plan_parse_json_and_compact():
    p = inject.FaultPlan.parse(
        '{"seed": 7, "faults": [{"site": "core.blocked.factor", '
        '"kind": "nan", "p": 0.5, "max_triggers": 2}]}')
    assert p.seed == 7
    assert p.specs[0].site == "core.blocked.factor"
    assert p.specs[0].p == 0.5 and p.specs[0].max_triggers == 2
    q = inject.FaultPlan.parse(
        "a.site=inf:p=0.25:max=3:skip=1;b.site=delay:param=0.5")
    assert len(q.specs) == 2
    assert q.specs[0] == inject.FaultSpec(site="a.site", kind="inf", p=0.25,
                                          max_triggers=3, skip=1, seed=0)
    assert q.specs[1].kind == "delay" and q.specs[1].param == 0.5
    for bad in ("", "siteonly", "a=notakind", "a=nan:bogus=1"):
        with pytest.raises(ValueError):
            inject.FaultPlan.parse(bad)


def test_poll_deterministic_and_bounded():
    def run():
        p = inject.FaultPlan([inject.FaultSpec(
            site="s", kind="nan", p=0.5, max_triggers=3, seed=4)], seed=9)
        with inject.plan(p) as ap:
            fired = [inject.poll("s") is not None for _ in range(40)]
            return fired, ap.stats()

    f1, s1 = run()
    f2, s2 = run()
    assert f1 == f2 and s1 == s2          # seeded: identical replay
    assert sum(f1) == 3                   # max_triggers bound holds
    assert s1["triggered"] == 3 and s1["polls"]["s"] == 40


def test_skip_delays_first_trigger():
    p = inject.FaultPlan([inject.FaultSpec(site="s", kind="raise",
                                           max_triggers=1, skip=2)])
    with inject.plan(p):
        inject.maybe_raise("s")
        inject.maybe_raise("s")
        with pytest.raises(inject.SimulatedFaultError):
            inject.maybe_raise("s")


def test_no_plan_is_inert_and_plans_do_not_stack():
    assert not inject.enabled()
    assert inject.poll("anything") is None
    a = np.ones((4, 4))
    assert inject.corrupt_operand("anything", a) is a
    p = inject.FaultPlan([inject.FaultSpec(site="s", kind="nan")])
    with inject.plan(p):
        assert inject.enabled()
        with pytest.raises(RuntimeError, match="already installed"):
            inject.install(p)
    assert not inject.enabled()


def test_corrupt_kinds():
    rng = np.random.default_rng(0)
    a = rng.standard_normal((32, 32))
    a_orig = a.copy()

    def corrupted(kind, **kw):
        p = inject.FaultPlan([inject.FaultSpec(site="s", kind=kind, **kw)])
        with inject.plan(p):
            return inject.corrupt_operand("s", a, panel=8)

    nan = corrupted("nan")
    assert nan is not a and np.isnan(nan).sum() == 32 * 8
    assert np.isinf(corrupted("inf")).any()
    bf = corrupted("bitflip")
    assert (bf != a).sum() == 1  # exactly one element changed
    nz = corrupted("near_zero_pivot")
    j = int(np.argmax((nz != a).any(axis=0)))
    np.testing.assert_allclose(nz[j:, j], a[j:, j] * 1e-30)
    np.testing.assert_array_equal(a, a_orig)  # corruption copies, never mutates


def test_env_var_activation_in_subprocess(tmp_path):
    """GAUSS_FAULTS installs a plan at import — the worker-subprocess
    channel; kind=kill exits with the distinctive code."""
    code = ("from gauss_tpu.resilience import inject\n"
            "assert inject.enabled()\n"
            "inject.maybe_kill('w')\n"
            "raise SystemExit(99)  # unreachable\n")
    env = {**os.environ, "GAUSS_FAULTS": "w=kill"}
    r = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == inject.KILL_EXIT_CODE, r.stderr


def test_multihost_straggler_and_kill_hooks(monkeypatch):
    """The dist.multihost hook points fire around initialize(): straggler
    sleeps, worker kill raises (in-process stand-in for os._exit)."""
    from gauss_tpu.dist import multihost

    calls = []
    monkeypatch.setattr(multihost, "_INITIALIZED", None)

    class _FakeDist:
        def initialize(self, **kw):
            calls.append(kw)

    import jax

    monkeypatch.setattr(jax, "distributed", _FakeDist())
    p = inject.FaultPlan([
        inject.FaultSpec(site="dist.multihost.straggler", kind="delay",
                         param=0.05),
        inject.FaultSpec(site="dist.multihost.worker", kind="raise"),
    ])
    with inject.plan(p):
        t0 = time.perf_counter()
        with pytest.raises(inject.SimulatedFaultError, match="worker"):
            multihost.initialize("127.0.0.1:1", 1, 0)
        assert time.perf_counter() - t0 >= 0.05
    assert calls  # the straggler delayed but did not prevent the join


# -- recover: gating + ladder ----------------------------------------------

def test_clean_solve_is_rung_zero_and_silent(rng):
    a, b = _system(rng, 32)
    with obs.run() as rec:
        res = recover.solve_resilient(a, b)
    assert res.rung == "blocked" and res.rung_index == 0 and not res.recovered
    assert res.rel_residual <= 1e-4
    assert not [e for e in rec.events if e["type"] == "recovery"]


def test_injected_corruption_recovers_with_events(rng):
    a, b = _system(rng, 32)
    x_ref = np.linalg.solve(a, b)
    plan = inject.FaultPlan.parse("core.blocked.factor=nan:max=1")
    with obs.run() as rec:
        with inject.plan(plan) as ap:
            res = recover.solve_resilient(a, b)
    assert ap.stats()["triggered"] == 1
    assert res.recovered and res.rung_index >= 1
    assert checks.elementwise_match(res.x, x_ref, 1e-4)
    evs = [e for e in rec.events if e["type"] == "recovery"]
    outcomes = [e["outcome"] for e in evs]
    assert outcomes[0] == "escalate" and outcomes[-1] == "recovered"
    assert evs[0]["trigger"] == "nonfinite_solution"
    assert {"rung", "attempt", "trigger", "outcome"} <= set(evs[0])
    faults = [e for e in rec.events if e["type"] == "fault"]
    assert faults and faults[0]["site"] == "core.blocked.factor"


def test_near_zero_pivot_recovery(rng):
    a, b = _system(rng, 32)
    plan = inject.FaultPlan.parse("core.blocked.factor=near_zero_pivot:max=1")
    with inject.plan(plan):
        res = recover.solve_resilient(a, b)
    assert res.rel_residual <= 1e-4


def test_persistent_both_engines_reaches_numpy(rng):
    a, b = _system(rng, 24)
    plan = inject.FaultPlan([
        inject.FaultSpec(site="core.blocked.factor", kind="inf",
                         max_triggers=None),
        inject.FaultSpec(site="core.gauss.solve", kind="inf",
                         max_triggers=None)])
    with inject.plan(plan):
        res = recover.solve_resilient(a, b)
    assert res.rung == "numpy_f64"
    assert res.rel_residual <= 1e-4
    assert len(res.escalations) == 4


def test_rank1_engine_ladder(rng):
    a, b = _system(rng, 24)
    plan = inject.FaultPlan.parse("core.gauss.solve=nan:max=1")
    with inject.plan(plan):
        res = recover.solve_resilient(a, b, engine="rank1")
    assert res.rel_residual <= 1e-4 and res.recovered


def test_multirhs_through_ladder(rng):
    a, b = _system(rng, 24, k=3)
    plan = inject.FaultPlan([
        inject.FaultSpec(site="core.blocked.factor", kind="nan",
                         max_triggers=None)])
    with inject.plan(plan):
        res = recover.solve_resilient(a, b)
    assert res.x.shape == (24, 3)
    assert checks.residual_norm(a, res.x, b, relative=True) <= 1e-4


def test_nonfinite_input_typed_error(rng):
    a, b = _system(rng, 16)
    a[3, 5] = np.nan
    with obs.run() as rec:
        with pytest.raises(recover.UnrecoverableSolveError) as ei:
            recover.solve_resilient(a, b)
    assert ei.value.trigger == "nonfinite_input"
    evs = [e for e in rec.events if e["type"] == "recovery"]
    assert evs and evs[-1]["outcome"] == "unrecoverable"


def test_singular_system_exhausts_ladder_typed(rng):
    a = np.zeros((12, 12))
    a[0, :] = 1.0  # rank 1: no rung can solve it
    b = np.ones(12)
    with pytest.raises(recover.UnrecoverableSolveError) as ei:
        recover.solve_resilient(a, b)
    assert len(ei.value.attempts) == 5
    rungs = [r for r, _ in ei.value.attempts]
    assert rungs == ["blocked", "pivot_safe", "ds_refine", "rank1",
                     "numpy_f64"]


def test_bad_requests_are_valueerrors(rng):
    a, b = _system(rng, 8)
    with pytest.raises(ValueError):
        recover.solve_resilient(a[:4], b)
    with pytest.raises(ValueError):
        recover.solve_resilient(a, b, rungs=("bogus",))
    with pytest.raises(ValueError):
        recover.default_rungs("bogus")


def test_zero_pivot_safe_factor_finite_on_singular():
    """The ladder's re-factor rung: an exactly singular matrix factors to a
    FINITE factor under zero_pivot_safe (min_abs_pivot records 0), where
    the default factorization NaN-poisons the trailing rows."""
    import jax.numpy as jnp

    a = np.ones((16, 16), dtype=np.float32)  # rank 1
    fac = blocked.lu_factor_blocked(jnp.asarray(a), panel=8,
                                    zero_pivot_safe=True)
    assert float(fac.min_abs_pivot) == 0.0
    assert np.isfinite(np.asarray(fac.m)).all()


# -- checkpoint ------------------------------------------------------------

def test_checkpoint_kill_resume_bit_identical(tmp_path, rng):
    n = 96
    a = _system(rng, n)[0].astype(np.float32)
    kw = dict(panel=16, chunk=2)
    clean = ckpt.lu_factor_blocked_chunked_checkpointed(
        a, tmp_path / "clean.npz", **kw)
    assert not (tmp_path / "clean.npz").exists()  # removed on success

    path = tmp_path / "killed.npz"
    plan = inject.FaultPlan([inject.FaultSpec(
        site="checkpoint.group", kind="raise", max_triggers=1, skip=2)])
    with obs.run() as rec:
        with inject.plan(plan):
            with pytest.raises(inject.SimulatedFaultError):
                ckpt.lu_factor_blocked_chunked_checkpointed(a, path, **kw)
        assert path.exists()  # the carry survived the kill
        resumed = ckpt.lu_factor_blocked_chunked_checkpointed(a, path, **kw)
    assert not path.exists()
    for f in ("m", "perm", "min_abs_pivot", "linv", "uinv"):
        np.testing.assert_array_equal(np.asarray(getattr(clean, f)),
                                      np.asarray(getattr(resumed, f)))
    evs = [e for e in rec.events if e["type"] == "checkpoint"]
    assert [e for e in evs if e["event"] == "save"]
    assert [e for e in evs if e["event"] == "resume"]
    # The resumed factor agrees with the one-shot chunked factorization.
    import jax.numpy as jnp

    one_shot = blocked.lu_factor_blocked_chunked(jnp.asarray(a), panel=16,
                                                 chunk=2)
    np.testing.assert_allclose(np.asarray(resumed.m),
                               np.asarray(one_shot.m), rtol=1e-5, atol=1e-5)


def test_checkpoint_mismatch_is_typed(tmp_path, rng):
    a = _system(rng, 64)[0].astype(np.float32)
    other = _system(rng, 64)[0].astype(np.float32)
    path = tmp_path / "ck.npz"
    plan = inject.FaultPlan([inject.FaultSpec(
        site="checkpoint.group", kind="raise", max_triggers=1, skip=1)])
    with inject.plan(plan):
        with pytest.raises(inject.SimulatedFaultError):
            ckpt.lu_factor_blocked_chunked_checkpointed(
                a, path, panel=16, chunk=1)
    # Resuming a DIFFERENT matrix (or different statics) against the saved
    # carry must refuse, not silently mix factorizations.
    with pytest.raises(ckpt.CheckpointMismatchError):
        ckpt.lu_factor_blocked_chunked_checkpointed(
            other, path, panel=16, chunk=1)
    with pytest.raises(ckpt.CheckpointMismatchError):
        ckpt.lu_factor_blocked_chunked_checkpointed(
            a, path, panel=16, chunk=2)
    # resume=False ignores the stale file and recomputes from scratch.
    fac = ckpt.lu_factor_blocked_chunked_checkpointed(
        a, path, panel=16, chunk=1, resume=False)
    assert np.isfinite(np.asarray(fac.m)).all()


def test_checkpoint_corrupt_file_typed_and_prev_fallback(tmp_path, rng):
    """Satellite: a truncated checkpoint is a typed CheckpointMismatchError
    (not a raw zipfile/numpy error), and when the previous generation was
    kept the resume silently falls back to it — a kill during the write of
    checkpoint K resumes from K−1 instead of failing."""
    n = 96
    a = _system(rng, n)[0].astype(np.float32)
    path = tmp_path / "ck.npz"
    kw = dict(panel=16, chunk=1, every_panels=1)
    plan = inject.FaultPlan([inject.FaultSpec(
        site="checkpoint.group", kind="raise", max_triggers=1, skip=3)])
    with inject.plan(plan):
        with pytest.raises(inject.SimulatedFaultError):
            ckpt.lu_factor_blocked_chunked_checkpointed(a, path, **kw)
    # Two generations on disk: current (K) and previous (K-1).
    prev = tmp_path / "ck.npz.prev"
    assert path.exists() and prev.exists()
    k_cur = ckpt.load_state(path)["meta"]["next_group"]
    assert ckpt.load_state(prev)["meta"]["next_group"] == k_cur - 1

    # Corrupt the CURRENT file (torn write): load is typed...
    path.write_bytes(path.read_bytes()[:100])
    with pytest.raises(ckpt.CheckpointMismatchError, match="corrupt"):
        ckpt.load_state(path)
    # ...and the checkpointed factorization resumes from K-1.
    with obs.run() as rec:
        resumed = ckpt.lu_factor_blocked_chunked_checkpointed(a, path, **kw)
    evs = [e for e in rec.events if e["type"] == "checkpoint"]
    assert [e for e in evs if e["event"] == "corrupt"]
    assert [e for e in evs if e["event"] == "fallback_prev"]
    res_ev = [e for e in evs if e["event"] == "resume"]
    assert res_ev and res_ev[0]["next_group"] == k_cur - 1
    clean = ckpt.lu_factor_blocked_chunked_checkpointed(
        a, tmp_path / "clean.npz", **kw)
    np.testing.assert_array_equal(np.asarray(resumed.m),
                                  np.asarray(clean.m))
    assert not path.exists() and not prev.exists()  # success cleans both


def test_checkpoint_both_generations_corrupt_is_typed(tmp_path, rng):
    a = _system(rng, 64)[0].astype(np.float32)
    path = tmp_path / "ck.npz"
    plan = inject.FaultPlan([inject.FaultSpec(
        site="checkpoint.group", kind="raise", max_triggers=1, skip=2)])
    with inject.plan(plan):
        with pytest.raises(inject.SimulatedFaultError):
            ckpt.lu_factor_blocked_chunked_checkpointed(
                a, path, panel=16, chunk=1, every_panels=1)
    for p in (path, tmp_path / "ck.npz.prev"):
        p.write_bytes(b"not a checkpoint")
    with pytest.raises(ckpt.CheckpointMismatchError, match="corrupt"):
        ckpt.lu_factor_blocked_chunked_checkpointed(a, path, panel=16,
                                                    chunk=1)
    # resume=False recomputes from scratch regardless.
    fac = ckpt.lu_factor_blocked_chunked_checkpointed(
        a, path, panel=16, chunk=1, resume=False)
    assert np.isfinite(np.asarray(fac.m)).all()


def test_stall_kind_sleeps_until_killed(tmp_path):
    """Satellite: kind=stall hangs the process forever (the hung-not-dead
    worker) — the subprocess stays alive past a grace period and only an
    external kill ends it, unlike kind=kill's immediate os._exit."""
    code = ("from gauss_tpu.resilience import inject\n"
            "print('armed', flush=True)\n"
            "inject.maybe_kill('w')\n"
            "print('unreachable', flush=True)\n")
    env = {**os.environ, "GAUSS_FAULTS": "w=stall"}
    p = subprocess.Popen([sys.executable, "-c", code], env=env, cwd=REPO,
                         stdout=subprocess.PIPE, text=True)
    try:
        assert p.stdout.readline().strip() == "armed"
        time.sleep(1.0)
        assert p.poll() is None          # still alive: stalled, not dead
    finally:
        p.kill()
        out, _ = p.communicate(timeout=60)
    assert "unreachable" not in out


# -- serve fallback lane reuses the ladder ---------------------------------

def test_serve_numpy_lane_is_ladder_backed(rng):
    from gauss_tpu.serve import ServeConfig, SolverServer

    from gauss_tpu.serve.cache import ExecutableCache

    # cache=: this test patches cache.get; the default cache is process-
    # shared now, so the patch must stay private to this server.
    srv = SolverServer(ServeConfig(ladder=(16, 32), panel=16,
                                   unhealthy_after=1, max_retries=0,
                                   retry_backoff_s=0.0,
                                   device_probe_cooldown_s=60.0,
                                   verify_gate=1e-4),
                       cache=ExecutableCache(8))

    def broken_get(key, builder=None, panel=None):
        raise RuntimeError("injected device failure")

    srv.cache.get = broken_get
    a, b = _system(rng, 12)
    bad = np.zeros((12, 12))
    bad[0, :] = 1.0
    with srv:
        ok = srv.solve(a, b)
        failed = srv.solve(bad, np.ones(12))
    assert ok.status == "ok" and ok.lane == "numpy"
    assert checks.residual_norm(a, ok.x, b, relative=True) <= 1e-4
    # An exactly-singular system through the degraded lane is a typed
    # VERDICT about the request, not a serving failure: the numpy_f64
    # rung's LinAlgError surfaces as SingularSystemError and the serving
    # layer maps it to the poison terminal.
    assert failed.status == "poison"
    assert "SingularSystemError" in failed.error


# -- chaos campaign --------------------------------------------------------

@pytest.mark.slow
def test_chaos_campaign_small_end_to_end(tmp_path):
    summary_path = tmp_path / "chaos.json"
    metrics_path = tmp_path / "chaos.jsonl"
    rc = chaos.main(["--cases", "12", "--serve-requests", "6",
                     "--seed", "5", "--tmpdir", str(tmp_path),
                     "--summary-json", str(summary_path),
                     "--metrics-out", str(metrics_path)])
    assert rc == 0
    summary = json.loads(summary_path.read_text())
    assert summary["kind"] == "chaos_campaign"
    assert summary["invariant_ok"]
    assert summary["injected"] >= 12
    assert summary["solver"]["counts"]["silent_wrong"] == 0
    assert summary["solver"]["counts"]["violation"] == 0
    assert summary["checkpoint"]["bit_identical"]
    # the supervised-fleet phase: kill + stall both recovered, bit-identical
    assert summary["fleet"]["violations"] == 0
    assert {c["kind"] for c in summary["fleet"]["cases"]} == {"kill",
                                                             "stall"}
    assert all(c.get("bit_identical") for c in summary["fleet"]["cases"]
               if c["outcome"] in ("ok", "recovered"))
    # regress ingest path
    recs = regress.ingest_file(summary_path)
    assert recs and all(r["kind"] == "chaos" for r in recs)
    assert any(r["metric"] == "chaos:solver/mean_rung" for r in recs)
    # the stream renders a resilience section. Fleet-phase faults fire
    # inside WORKER subprocesses (their fault events live in the job's
    # per-worker streams), so the campaign stream carries everything else.
    events = obs.read_events(metrics_path)
    rs = summarize.resilience_summary(events)
    assert rs["injections"]["total"] == (summary["injected"]
                                         - summary["fleet"]["injected"])
    # ...and a fleet section from the supervisor's events.
    assert summarize.fleet_summary(events)["solves"] == 3


def test_chaos_history_records_shape():
    recs = chaos.history_records(
        {"solver": {"mean_rung": 2.1, "typed_error_rate": 0.08,
                    "cases": 100},
         "wall_s": 10.0})
    assert ("chaos:solver/mean_rung", 2.1, "rung") in recs
    assert ("chaos:solver/typed_error_rate", 0.08, "ratio") in recs
    assert ("chaos:solver/s_per_case", 0.1, "s") in recs
    assert chaos.history_records({"solver": {}, "wall_s": None}) == []


# -- summarize resilience section ------------------------------------------

def test_resilience_summary_section_and_json(tmp_path):
    with obs.run(metrics_out=str(tmp_path / "rs.jsonl")) as rec:
        obs.emit("fault", site="core.blocked.factor", kind="nan", seq=1)
        obs.emit("fault", site="serve.cache.compile", kind="compile_fail",
                 seq=1)
        obs.emit("recovery", trigger="nonfinite_solution", rung="blocked",
                 rung_index=0, attempt=1, outcome="escalate")
        obs.emit("recovery", trigger="nonfinite_solution", rung="pivot_safe",
                 rung_index=1, attempt=2, outcome="recovered",
                 rel_residual=1e-9)
        obs.emit("recovery", trigger="residual", rung="numpy_f64",
                 attempt=5, outcome="unrecoverable")
        obs.emit("checkpoint", event="save", path="x", next_group=2)
        obs.emit("checkpoint", event="resume", path="x", next_group=2)
    events = obs.read_events(tmp_path / "rs.jsonl")
    rs = summarize.resilience_summary(events)
    assert rs["injections"]["total"] == 2
    assert rs["injections"]["by_site"] == {"core.blocked.factor": 1,
                                           "serve.cache.compile": 1}
    assert rs["recoveries"] == {"total": 1, "by_rung": {"pivot_safe": 1}}
    assert rs["escalations"] == 1 and rs["unrecoverable"] == 1
    assert rs["checkpoints"] == {"save": 1, "resume": 1}
    text = summarize.summarize_events(events, rec.run_id)
    assert "resilience:" in text and "pivot_safe" in text
    payload = summarize.run_summary(events, rec.run_id)
    json.dumps(payload)
    assert payload["resilience"]["recoveries"]["total"] == 1
    # Runs without resilience events carry no section.
    with obs.run(metrics_out=str(tmp_path / "plain.jsonl")) as r2:
        obs.emit("custom")
    plain = obs.read_events(tmp_path / "plain.jsonl")
    assert summarize.resilience_summary(plain) == {}
    assert "resilience:" not in summarize.summarize_events(plain, r2.run_id)
