"""PR 10 — fused panel+trailing kernel, buffer donation, and the
compiled-out-hooks fast path.

Covers the reclaim contracts: fused-vs-unfused bit-identity at matching
tiles, 1e-4 residuals across the (panel, chunk, n) grid including the
non-multiple-of-panel edge, donated-buffer inspection on the jitted
factor/solve steps, the callback-free plain-path jaxpr, the fused-vs-ABFT
deterministic fallback, the doctor forbidden-phase CI gate, and the
tightened regression ratchet.
"""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from gauss_tpu.core import blocked  # noqa: E402
from gauss_tpu.kernels import panel_fused_pallas as pf  # noqa: E402
from gauss_tpu.kernels.panel_pallas import panel_factor_pallas  # noqa: E402
from gauss_tpu.verify import checks  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def rng():
    return np.random.default_rng(258458)


def _system(rng, n):
    a = rng.standard_normal((n, n)).astype(np.float32)
    a[np.arange(n), np.arange(n)] += float(n)
    return a, rng.standard_normal(n).astype(np.float32)


@pytest.mark.parametrize("h,panel,kb,ct,seg,fseg", [
    (96, 16, 32, 16, 8, 8),      # mid-block panel, small tiles
    (96, 16, 0, 32, 16, 4),      # first panel, wider tiles
    (64, 32, 0, 64, 32, 32),     # single-segment apply (fseg == panel)
    (80, 16, 64, 16, 4, 16),     # last panel: trailing empty, copies only
])
def test_fused_bit_identical_to_unfused_pair(rng, h, panel, kb, ct, seg,
                                             fseg):
    """The fused kernel == the unfused pair (panel_factor_pallas launch +
    trailing_update_pallas launch) BIT FOR BIT at matching tiles — the
    fusion deletes the HBM round-trip between the launches, never a bit
    of the math (shared _factor_body / _trailing_tile_update)."""
    block = jnp.asarray(rng.standard_normal((h, h)).astype(np.float32))
    p, ipiv, perm, mp, upd = pf.panel_trailing_fused_pallas(
        block, kb, kb, panel=panel, ct=ct, seg=seg, fseg=fseg)
    p2, ipiv2, perm2, mp2 = panel_factor_pallas(block[:, kb:kb + panel],
                                                kb, seg=seg)
    mult, pt = pf.reconstruct_mult_pt(p2, ipiv2, perm2, kb, panel)
    upd2 = pf.trailing_update_pallas(block, mult, pt, kb, ct=ct, fseg=fseg)
    np.testing.assert_array_equal(np.asarray(p), np.asarray(p2))
    np.testing.assert_array_equal(np.asarray(ipiv), np.asarray(ipiv2))
    np.testing.assert_array_equal(np.asarray(perm), np.asarray(perm2))
    assert float(mp) == float(mp2)
    np.testing.assert_array_equal(np.asarray(upd), np.asarray(upd2))
    # Columns at or left of the panel pass through untouched.
    np.testing.assert_array_equal(np.asarray(upd)[:, :kb + panel],
                                  np.asarray(block)[:, :kb + panel])


def test_fused_trailing_matches_xla_reference(rng):
    """The fused trailing update reproduces _install_and_update's
    L11^-1-based U12 + masked GEMM to f32 rounding (different float
    association, same math) — the 1e-4 gate's foundation."""
    from jax import lax

    h, panel, kb = 96, 16, 32
    block = jnp.asarray(rng.standard_normal((h, h)).astype(np.float32))
    p, ipiv, perm, mp, upd = pf.panel_trailing_fused_pallas(
        block, kb, kb, panel=panel, ct=16, seg=8, fseg=8)
    ref, _, _ = blocked._install_and_update(
        block[perm], kb, h, panel, p, lax.Precision.HIGHEST, jnp.float32)
    fused_m = jnp.asarray(upd)[perm].at[:, kb:kb + panel].set(p)
    np.testing.assert_allclose(np.asarray(fused_m), np.asarray(ref),
                               atol=5e-5, rtol=5e-5)


@pytest.mark.parametrize("n,panel,chunk", [
    (96, 16, 2), (100, 16, 2),   # non-multiple-of-panel edge
    (64, 32, 1),                 # single-panel groups (fused skipped)
    (130, 32, 2), (96, 48, 2),   # panel not dividing n
])
def test_fused_factor_routes_residual(rng, n, panel, chunk):
    """panel_impl='fused' through all three factorization forms: every
    route must clear the 1e-4 residual gate, including the padded edge."""
    a, b = _system(rng, n)
    a64, b64 = np.asarray(a, np.float64), np.asarray(b, np.float64)
    routes = [
        blocked.lu_factor_blocked(jnp.asarray(a), panel=panel,
                                  panel_impl="fused"),
        blocked.lu_factor_blocked_unrolled(jnp.asarray(a), panel=panel,
                                           panel_impl="fused"),
        blocked.lu_factor_blocked_chunked(jnp.asarray(a), panel=panel,
                                          chunk=chunk, panel_impl="fused"),
    ]
    for fac in routes:
        x = np.asarray(blocked.lu_solve(fac, jnp.asarray(b)), np.float64)
        assert checks.residual_norm(a64, x, b64) < 1e-4


def test_fused_checkpointed_matches_oneshot(rng, tmp_path):
    """The checkpointed path shares _factor_group, so a fused chunked
    factorization and its checkpointed twin stay bit-identical."""
    from gauss_tpu.resilience import checkpoint as ckpt

    a, _ = _system(rng, 96)
    f1 = blocked.lu_factor_blocked_chunked(jnp.asarray(a), panel=16,
                                           chunk=2, panel_impl="fused")
    f2 = ckpt.lu_factor_blocked_chunked_checkpointed(
        a, str(tmp_path / "ck.npz"), panel=16, chunk=2,
        panel_impl="fused")
    for fld in ("m", "perm", "min_abs_pivot", "linv", "uinv"):
        np.testing.assert_array_equal(np.asarray(getattr(f1, fld)),
                                      np.asarray(getattr(f2, fld)))


def test_abft_falls_back_to_unfused_deterministically(rng):
    """abft=True + panel_impl='fused': the checksum rider deterministically
    pins the UNFUSED pair (the fused kernel does not thread the carry), so
    the abft factor stays bit-identical to the unfused abft=False form and
    the rider still verifies — the fused-vs-ABFT contract (ISSUE 10), and
    the zero-overhead sentinel's bit-identity foundation."""
    a, _ = _system(rng, 96)
    fab = blocked.lu_factor_blocked_chunked(jnp.asarray(a), panel=16,
                                            chunk=2, panel_impl="fused",
                                            abft=True)
    ref = blocked.lu_factor_blocked_chunked(jnp.asarray(a), panel=16,
                                            chunk=2, panel_impl="auto")
    np.testing.assert_array_equal(np.asarray(fab.m), np.asarray(ref.m))
    np.testing.assert_array_equal(np.asarray(fab.perm), np.asarray(ref.perm))
    assert float(jnp.max(fab.abft_err)) < 1e-2  # healthy run: noise only
    # And the resolver itself: an ABFT carry always rejects the fused form.
    assert blocked._use_fused("fused", 2048, 128, 2048, carried=True) is False
    assert blocked._use_fused("auto", 2048, 128, 2048, carried=True) is False


def test_use_fused_routing(monkeypatch):
    """The selection contract: TPU-only in auto mode, VMEM-gated, explicit
    'fused' forces (with the clear sizing error past the budget on real
    TPUs), zero_pivot_safe and narrow trailing always fall back."""
    # CPU auto never selects the fused kernel (the plain CPU path is
    # measured without interpret-mode kernels).
    assert blocked._use_fused("auto", 2048, 128, 2048) is False
    # Explicit request runs anywhere (interpret mode off-TPU).
    assert blocked._use_fused("fused", 96, 16, 96) is True
    assert blocked._use_fused("jax", 2048, 128, 2048) is False
    assert blocked._use_fused("pallas", 2048, 128, 2048) is False
    assert blocked._use_fused("fused", 96, 16, 96,
                              zero_pivot_safe=True) is False
    assert blocked._use_fused("fused", 96, 16, 16) is False  # no trailing
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert blocked._use_fused("auto", 2048, 128, 2048) is True
    assert blocked._use_fused("auto", 2048, 256, 2048) is True
    # Past the budget: auto falls back, explicit raises the sizing error.
    monkeypatch.setattr(blocked, "PANEL_VMEM_BUDGET", 1_000_000)
    assert blocked._use_fused("auto", 2048, 128, 2048) is False
    with pytest.raises(ValueError, match="fused working set"):
        blocked._use_fused("fused", 2048, 128, 2048)


def test_fused_tiles_consult_tuned_store(monkeypatch):
    """The tile/segment axes resolve through tune.apply (op panel_fused)
    — the PR-7 single-source rule: sweep winners override the seeds."""
    from gauss_tpu.tune import apply as tapply
    from gauss_tpu.tune import space as tspace

    seen = []

    def fake_override(op, n, name, dtype="float32", engine="blocked"):
        seen.append((op, name))
        return {"ct": 32, "seg": 8, "fseg": 4}.get(name)

    monkeypatch.setattr(tapply, "override", fake_override)
    ct, seg, fseg = pf._resolve_tiles(96, 96, 16, jnp.float32, None, None,
                                      None)
    assert (ct, seg, fseg) == (32, 8, 4)
    assert ("panel_fused", "ct") in seen
    # Explicit values are honored verbatim, no consult.
    seen.clear()
    ct, seg, fseg = pf._resolve_tiles(96, 96, 16, jnp.float32, 16, 8, 8)
    assert (ct, seg, fseg) == (16, 8, 8) and not seen
    # The axes are declared in the swept space with the shipped seeds.
    names = {ax.name: ax.seed for ax in tspace.space_for("panel_fused")}
    assert names["ct"] == tspace.FUSED_CT_SEED
    assert names["fseg"] == tspace.FUSED_FSEG_SEED
    assert names["seg"] == tspace.PANEL_SEG_SEED


def _jaxpr_primitives(jaxpr, acc):
    for eqn in jaxpr.eqns:
        acc.add(eqn.primitive.name)
        for v in eqn.params.values():
            if hasattr(v, "jaxpr"):
                _jaxpr_primitives(v.jaxpr, acc)
            elif isinstance(v, (list, tuple)):
                for w in v:
                    if hasattr(w, "jaxpr"):
                        _jaxpr_primitives(w.jaxpr, acc)
    return acc


@pytest.mark.parametrize("unroll", ["auto", True, False, "chunked"])
def test_plain_path_jaxpr_free_of_hook_callsites(rng, unroll):
    """resolve_factor's fast-path contract: with no checkpoint path and no
    ABFT carry, the selected factorization traces to a jaxpr with NO host
    callsites — no io_callback/pure_callback/debug primitives anywhere.
    Hooks cost nothing unless enabled."""
    a, _ = _system(rng, 64)
    factor = blocked.resolve_factor(64, unroll)
    jaxpr = jax.make_jaxpr(lambda x: factor(x, panel=16))(jnp.asarray(a))
    prims = _jaxpr_primitives(jaxpr.jaxpr, set())
    forbidden = {p for p in prims
                 if "callback" in p or p.startswith("debug_")}
    assert not forbidden, f"hook callsites on the plain path: {forbidden}"


def test_resolve_factor_fastpath_routing(tmp_path):
    """The extended resolve_factor contract: checkpoint_path routes to the
    (only) host-stepped form, abft to the checksum-carrying single
    program, and the two refuse to combine."""
    from functools import partial as _p

    from gauss_tpu.resilience.checkpoint import \
        lu_factor_blocked_chunked_checkpointed

    f = blocked.resolve_factor(256, "auto",
                               checkpoint_path=str(tmp_path / "c.npz"))
    assert isinstance(f, _p)
    assert f.func is lu_factor_blocked_chunked_checkpointed
    f = blocked.resolve_factor(256, "auto", abft=True)
    assert isinstance(f, _p) and f.keywords.get("abft") is True
    with pytest.raises(ValueError, match="mutually exclusive"):
        blocked.resolve_factor(256, "auto", checkpoint_path="x", abft=True)


def test_donation_marked_in_lowering_and_honored(rng):
    """Donation asserted two ways: the lowered module carries the
    input-output alias attribute, and on a backend that honors donation
    (CPU, jax >= 0.4.x) the donated operand buffer is actually consumed.
    The undonated twin leaves its operand alive."""
    a, _ = _system(rng, 64)
    low = blocked.lu_factor_blocked_donating.lower(jnp.asarray(a), panel=16)
    assert "tf.aliasing_output" in low.as_text()
    # And in the compiled executable: the input/output alias survives to
    # the backend (jax.jit(...).lower(...).compile() inspection).
    compiled = low.compile()
    assert any("alias" in t.lower() for t in compiled.as_text().split("\n")
               if "input_output" in t.lower() or "alias" in t.lower())
    op = jnp.asarray(a)
    blocked.lu_factor_blocked_donating(op, panel=16)
    assert op.is_deleted()
    op2 = jnp.asarray(a)
    blocked.lu_factor_blocked(op2, panel=16)
    assert not op2.is_deleted()


def test_refine_ds_donates_x0(rng):
    """The ds-refine loop donates its solution seed (the fresh initial
    solve every call site passes)."""
    from gauss_tpu.core import dsfloat

    a, b = _system(rng, 64)
    a64 = np.asarray(a, np.float64)
    fac = blocked.lu_factor_blocked(jnp.asarray(a), panel=16)
    b_ds = dsfloat.to_ds(np.asarray(b, np.float64))
    x0 = blocked.lu_solve(fac, b_ds.hi)
    x = dsfloat.refine_ds(fac, dsfloat.to_ds(a64.T), b_ds, x0, iters=2)
    assert x0.is_deleted()
    x64 = dsfloat.ds_to_f64(x)
    assert checks.residual_norm(a64, x64, np.asarray(b, np.float64)) < 1e-4


def test_serve_executables_donate(rng):
    """The serve cache's factor/solve lanes donate their freshly-staged
    operand stacks (matrix stack on factor, RHS stack on solve) and still
    refine through the retained factors."""
    from gauss_tpu.serve.cache import BatchedExecutable, CacheKey

    key = CacheKey(bucket_n=32, nrhs=1, batch=2, dtype="float32",
                   engine="blocked", refine_steps=1)
    exe = BatchedExecutable(key)
    a = np.stack([_system(rng, 32)[0].astype(np.float64)
                  for _ in range(2)])
    b = rng.standard_normal((2, 32, 1))
    x = exe.solve(a, b)
    r = np.linalg.norm(np.einsum("bij,bjk->bik", a, x) - b)
    assert r < 1e-4
    # The solve lane's lowering carries the donation alias at every
    # bucket; the factor lane donates only at panel-multiple buckets
    # (a padded donation would be unusable).
    fac = exe._factor(a.astype(np.float32))
    low = exe._solve.lower(fac, b.astype(np.float32))
    assert "tf.aliasing_output" in low.as_text()


def test_checkpoint_group_step_donates(rng, tmp_path):
    """The host-stepped checkpoint route donates its per-group carry (the
    copy-per-step kill) and stays bit-identical to the one-shot chunked
    program — kill/resume semantics untouched (tier-1 resilience tests
    cover the kill path)."""
    from gauss_tpu.resilience import checkpoint as ckpt

    a, _ = _system(rng, 96)
    f1 = ckpt.lu_factor_blocked_chunked_checkpointed(
        a, str(tmp_path / "ck.npz"), panel=16, chunk=2)
    f2 = blocked.lu_factor_blocked_chunked(jnp.asarray(a), panel=16,
                                           chunk=2)
    for fld in ("m", "perm", "min_abs_pivot", "linv", "uinv"):
        np.testing.assert_array_equal(np.asarray(getattr(f1, fld)),
                                      np.asarray(getattr(f2, fld)))


def test_doctor_forbidden_phase_gate():
    """The CI gate: host_group_step/hook_sync present in the candidate
    stream exits 1; a clean candidate exits 0."""
    from gauss_tpu.obs import doctor

    r3 = os.path.join(REPO, "reports", "doctor_r3like.jsonl")
    r5 = os.path.join(REPO, "reports", "doctor_r5like.jsonl")
    assert doctor.main([r3, r5, "--forbid", "host_group_step,hook_sync",
                        "--json"]) == 1
    assert doctor.main([r3, r3, "--forbid", "host_group_step,hook_sync",
                        "--json"]) == 0
    # The matcher also catches dotted descendants.
    diff = {"phases": [{"phase": "host_group_step.factor", "b_calls": 3,
                        "b_s": 0.1}]}
    assert doctor.forbidden_phases(diff, ["host_group_step"])


def test_ratchet_tightened_ceiling():
    """The reclaimed record's tightened per-metric ceiling: an r5-class
    1.4-1.5x 'hooks tax' regression now FAILS the ratchet instead of
    hiding under the generic 1.5x epoch envelope; the refined metric is
    ratcheted too."""
    from gauss_tpu.obs import regress

    best = regress.RATCHET_BASELINES["gauss_n2048_wallclock"]
    assert regress.RATCHET_CEILINGS["gauss_n2048_wallclock"] < \
        regress.RATCHET_MAX_RATIO
    bad = regress.evaluate_ratchet("gauss_n2048_wallclock", best * 1.45)
    assert bad["status"] == "out-of-band"
    ok = regress.evaluate_ratchet("gauss_n2048_wallclock", best * 1.3)
    assert ok["status"] == "ok"
    refined = regress.evaluate_ratchet(
        "gauss_n2048_wallclock:refined",
        regress.RATCHET_BASELINES["gauss_n2048_wallclock:refined"] * 1.2)
    assert refined["status"] == "ok"


def test_regress_check_ratchet_flag():
    """`regress check --ratchet` applies the ratchet gate in CI: the
    committed record round passes; a synthetic slow record fails."""
    import json

    from gauss_tpu.obs import regress

    hist = os.path.join(REPO, "reports", "history.jsonl")
    r03 = os.path.join(REPO, "BENCH_r03.json")
    assert regress.main(["check", r03, "--ratchet", "--history", hist]) == 0
    slow = os.path.join(REPO, "reports", "doctor_r3like.jsonl")  # unused
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        json.dump({"parsed": {"metric": "gauss_n2048_wallclock",
                              "value": 0.00225, "unit": "s"}}, f)
        bad_path = f.name
    try:
        # 2.25 ms is inside the median band (the r5 norm) but past the
        # tightened 1.35x ratchet ceiling — exactly the regression shape
        # the reclaim forbids from ever becoming normal again.
        assert regress.main(["check", bad_path, "--ratchet",
                             "--history", hist]) == 1
        assert regress.main(["check", bad_path, "--history", hist]) == 0
    finally:
        os.unlink(bad_path)


def test_reclaim_epochs_in_history():
    """The reclaim run's measured CPU-proxy epochs are committed history
    (regress-ingestable) and sit at or below the PR-6 post-guard mark."""
    from gauss_tpu.obs import regress

    hist = regress.load_history(
        os.path.join(REPO, "reports", "history.jsonl"))
    vals = [r["value"] for r in hist
            if r["metric"] == "reclaim:gauss_n2048_cpu_plain_s_per_solve"]
    assert len(vals) >= 3
    assert min(vals) <= 1.3749


def test_bench_provenance_helpers():
    """bench.py's fused/donated provenance fields reflect the actual
    routing primitives (False/True on CPU respectively at the headline
    shape)."""
    assert blocked._use_fused("auto", 2048, 256, 2048) is False  # CPU
    assert 2048 % 256 == 0  # the donated condition at the headline shape
