"""Poison-isolation tests: the admission operand scan (typed ``poison``
rejects BEFORE the journal admit), the recovery ladder's typed
:class:`SingularSystemError` verdict, batch bisection blame-hunting, the
blame-journal records (per-boot death counts, rotation carry), replay-time
quarantine (solo execution at K deaths, typed reject past K), the
journal-adoption carry of a dead replica's death counts, the supervisor's
uncharged quarantined respawns, the loadgen ``poison:`` mix token, and the
regress/summarize ingest for ``kind: poison_campaign``.

All CPU (conftest pins the platform); servers share one module-scoped
executable cache so the jitted batch executables compile once.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

from gauss_tpu import obs
from gauss_tpu.obs import regress
from gauss_tpu.resilience import recover
from gauss_tpu.serve import (
    STATUS_POISON,
    ServeConfig,
    SolverServer,
    durable,
    net,
    poison_scan,
)
from gauss_tpu.serve.cache import ExecutableCache
from gauss_tpu.verify import checks

GATE = 1e-4


@pytest.fixture(scope="module")
def shared_cache():
    return ExecutableCache(64)


@pytest.fixture()
def rng():
    return np.random.default_rng(777201)


def _system(rng, n):
    a = rng.standard_normal((n, n))
    a[np.arange(n), np.arange(n)] += float(n)
    return a, rng.standard_normal(n)


def _config(journal_dir, **over):
    kw = dict(ladder=(32,), max_batch=4, panel=16, refine_steps=1,
              verify_gate=GATE, journal_dir=journal_dir,
              journal_fsync_batch=2)
    kw.update(over)
    return ServeConfig(**kw)


def _journal_with_admit(jd, a, b, *, rid="r1", blame_boots=()):
    """A dead worker's journal: one live admit, one blame record per boot
    in ``blame_boots`` — the evidence shape ``death_counts`` folds."""
    jr = durable.RequestJournal(jd, fsync_batch=1, rotate_records=10_000)
    jr.append_admit(id=1, request_id=rid, trace="t1", a=a, b=b,
                    was_vector=True, deadline_unix=None, dtype=None,
                    structure=None)
    for boot in blame_boots:
        jr.append_blame(ids=[1], rids=[rid], boot=boot)
    jr.close()


# -- the admission scan ----------------------------------------------------

def test_poison_scan_typed_reasons(rng):
    a, b = _system(rng, 8)
    assert poison_scan(a, b) is None
    bad = a.copy()
    bad[2, 3] = np.nan
    assert "non-finite" in poison_scan(bad, b)
    bad_b = b.copy()
    bad_b[0] = np.inf
    assert "non-finite" in poison_scan(a, bad_b)


def test_submit_rejects_nonfinite_before_journal_admit(rng, shared_cache,
                                                       tmp_path):
    """The crash-loop-proofing satellite: a non-finite operand draws its
    typed terminal BEFORE the journal admit — a poison the journal never
    saw cannot be replayed into a crash loop."""
    jd = str(tmp_path / "j")
    a, b = _system(rng, 12)
    a[0, 0] = np.nan
    with SolverServer(_config(jd), cache=shared_cache) as srv:
        res = srv.solve(a, b, request_id="nanpill", timeout=60.0)
        assert res.status == STATUS_POISON
        assert "poisoned operands" in res.error
    st = durable.scan(jd)
    assert "nanpill" not in st.by_rid
    assert not any(d.get("rid") == "nanpill" for d in st.admits.values())


def test_singular_system_typed_verdict(rng):
    """An exactly-singular system is a VERDICT about the operands: the
    host rung raises the typed subclass (still an UnrecoverableSolveError
    for existing callers) with trigger ``singular_matrix``."""
    a = np.zeros((12, 12))
    a[0, :] = 1.0
    with pytest.raises(recover.SingularSystemError) as ei:
        recover.solve_resilient(a, np.ones(12))
    assert isinstance(ei.value, recover.UnrecoverableSolveError)
    assert ei.value.trigger == "singular_matrix"
    assert ei.value.attempts  # the escalation trail survives the re-raise


def test_served_singular_is_poison_not_failure(rng, shared_cache):
    a, b = _system(rng, 14)
    a[7, :] = 0.0
    with SolverServer(_config(None), cache=shared_cache) as srv:
        res = srv.solve(a, b, timeout=120.0)
    assert res.status == STATUS_POISON
    assert "SingularSystemError" in res.error


def test_nonfinite_solution_never_resolves_ok_without_gate(rng,
                                                           shared_cache):
    """The non-finite rescue is unconditional on ``verify_gate``: with no
    gate configured, a singular system's NaN/Inf batched solution must
    still route to the host ladder and draw the typed verdict — never an
    ``ok`` carrying non-finite x."""
    a, b = _system(rng, 16)
    a[8, :] = 0.0
    cfg = _config(None, verify_gate=None)
    with obs.run() as rec:
        with SolverServer(cfg, cache=shared_cache) as srv:
            res = srv.solve(a, b, timeout=120.0)
    assert res.status == STATUS_POISON
    assert "SingularSystemError" in res.error
    assert rec.counters.get("serve.nonfinite_rescues", 0) >= 1


# -- batch bisection -------------------------------------------------------

def test_bisection_isolates_culprit_and_reserves_innocents(
        rng, shared_cache, tmp_path):
    from gauss_tpu.serve.poisoncheck import SENTINEL, _TrippingCache

    jd = str(tmp_path / "j")
    cfg = _config(jd, batch_linger_s=0.25)
    innocents = {f"i{j}": _system(rng, 8 + 4 * j) for j in range(3)}
    pa, pb = _system(rng, 16)
    pa[0, 0] = SENTINEL
    with obs.run() as rec:
        with SolverServer(cfg, cache=_TrippingCache(shared_cache)) as srv:
            handles = [("pill", srv.submit(pa, pb, request_id="pill"))]
            for rid, (a, b) in innocents.items():
                handles.append((rid, srv.submit(a, b, request_id=rid)))
            results = {rid: h.result(timeout=120.0) for rid, h in handles}
    assert results["pill"].status == STATUS_POISON
    assert "poison batch member" in results["pill"].error
    for rid, (a, b) in innocents.items():
        res = results[rid]
        assert res.status == "ok", (rid, res.status, res.error)
        assert checks.residual_norm(a, res.x, b, relative=True) <= GATE
    assert rec.counters.get("serve.bisections", 0) >= 1
    # innocents re-served under their ORIGINAL journal ids: one terminal
    # each, no re-admits
    st = durable.scan(jd)
    assert st.by_rid["pill"]["status"] == STATUS_POISON
    for rid in innocents:
        assert st.by_rid[rid]["status"] == "ok"


def test_toplevel_singleton_failure_stays_failed(rng, shared_cache):
    """Only the bisection hunt proves batch-relative blame: a lone request
    failing non-transiently keeps the pre-existing ``failed`` shape."""
    from gauss_tpu.serve.poisoncheck import SENTINEL, _TrippingCache

    a, b = _system(rng, 16)
    a[0, 0] = SENTINEL
    with SolverServer(_config(None), cache=_TrippingCache(shared_cache)) \
            as srv:
        res = srv.solve(a, b, timeout=120.0)
    assert res.status == "failed"
    assert "poison batch member" not in (res.error or "")


# -- blame records / death counts ------------------------------------------

def test_blame_records_boot_increments_and_death_counts(rng, tmp_path):
    jd = str(tmp_path / "j")
    a, b = _system(rng, 6)
    jr = durable.RequestJournal(jd, fsync_batch=1, rotate_records=10_000)
    assert jr.boot == 1
    jr.append_admit(id=1, request_id="r1", trace="t", a=a, b=b,
                    was_vector=True, deadline_unix=None, dtype=None,
                    structure=None)
    jr.append_admit(id=2, request_id="r2", trace="t", a=a, b=b,
                    was_vector=True, deadline_unix=None, dtype=None,
                    structure=None)
    jr.append_blame(ids=[1, 2], rids=["r1", "r2"])
    jr.append_blame(ids=[1])  # re-dispatch, SAME boot: still one death
    jr.close()
    jr2 = durable.RequestJournal(jd, fsync_batch=1, rotate_records=10_000)
    assert jr2.boot == 2  # restart = next boot
    jr2.append_blame(ids=[1])
    jr2.append_terminal(id=2, request_id="r2", trace="t", status="ok",
                        x=b, lane="batched", rel_residual=1e-9)
    jr2.close()
    counts = durable.scan(jd).death_counts()
    assert counts == {1: 2}  # r1: two distinct boots; r2: terminated
    assert durable.quarantinable_ids(jd) == {1: 2}
    assert durable.quarantinable_ids(jd, k=3) == {}
    assert durable.quarantinable_ids(str(tmp_path / "missing")) == {}


def test_rotation_carries_blame_for_live_admits(rng, tmp_path):
    jd = str(tmp_path / "j")
    a, b = _system(rng, 6)
    jr = durable.RequestJournal(jd, fsync_batch=1, rotate_records=8)
    jr.append_admit(id=1, request_id="r1", trace="t", a=a, b=b,
                    was_vector=True, deadline_unix=None, dtype=None,
                    structure=None)
    jr.append_blame(ids=[1], rids=["r1"])
    for i in range(2, 12):  # push past rotate_records
        jr.append_admit(id=i, request_id=f"r{i}", trace="t", a=a, b=b,
                        was_vector=True, deadline_unix=None, dtype=None,
                        structure=None)
        jr.append_terminal(id=i, request_id=f"r{i}", trace="t",
                           status="ok", x=b, lane="batched",
                           rel_residual=1e-9)
    jr.close()
    assert durable.scan(jd).death_counts() == {1: 1}


# -- replay-time quarantine ------------------------------------------------

def test_replay_quarantines_at_k_deaths_and_solves_solo(rng, shared_cache,
                                                        tmp_path):
    jd = str(tmp_path / "j")
    a, b = _system(rng, 12)
    _journal_with_admit(jd, a, b, blame_boots=(1, 2))
    with obs.run() as rec:
        with SolverServer(_config(jd, quarantine_deaths=2),
                          cache=shared_cache) as srv:
            assert srv.last_resume["quarantined"] == 1
            res = srv.solve(a, b, request_id="r1", timeout=120.0)
    assert res.status == "ok"
    assert checks.residual_norm(a, res.x, b, relative=True) <= GATE
    assert any(ev.get("type") == "quarantine" and ev.get("action") == "solo"
               for ev in rec.events)
    st = durable.scan(jd)
    assert st.by_rid["r1"]["status"] == "ok"


def test_replay_rejects_typed_past_k_deaths(rng, shared_cache, tmp_path):
    jd = str(tmp_path / "j")
    a, b = _system(rng, 12)
    _journal_with_admit(jd, a, b, blame_boots=(1, 2, 3))
    with SolverServer(_config(jd, quarantine_deaths=2),
                      cache=shared_cache) as srv:
        assert srv.last_resume["poisoned"] == 1
        res = srv.solve(a, b, request_id="r1", timeout=60.0)
    assert res.status == STATUS_POISON
    assert "quarantined" in res.error
    st = durable.scan(jd)
    assert st.by_rid["r1"]["status"] == STATUS_POISON


def test_replay_scans_journaled_operands(rng, shared_cache, tmp_path):
    """A poisoned admit that somehow reached the journal (older build,
    scan off) must be typed-rejected at replay, never dispatched."""
    jd = str(tmp_path / "j")
    a, b = _system(rng, 12)
    a[3, 3] = np.nan
    _journal_with_admit(jd, a, b)
    with SolverServer(_config(jd), cache=shared_cache) as srv:
        assert srv.last_resume["poisoned"] == 1
    st = durable.scan(jd)
    assert st.by_rid["r1"]["status"] == STATUS_POISON
    assert "poisoned operands" in st.by_rid["r1"]["error"]


def test_quarantine_zero_disables_the_policy(rng, shared_cache, tmp_path):
    jd = str(tmp_path / "j")
    a, b = _system(rng, 12)
    _journal_with_admit(jd, a, b, blame_boots=(1, 2, 3, 4))
    with SolverServer(_config(jd, quarantine_deaths=0),
                      cache=shared_cache) as srv:
        res = srv.solve(a, b, request_id="r1", timeout=120.0)
    assert res.status == "ok"


# -- journal adoption carries the evidence ---------------------------------

def test_adopt_journal_quarantines_implicated_rid(rng, shared_cache,
                                                  tmp_path):
    victim = str(tmp_path / "victim")
    a, b = _system(rng, 12)
    _journal_with_admit(victim, a, b, blame_boots=(1, 2))
    with obs.run() as rec:
        with SolverServer(_config(str(tmp_path / "survivor"),
                                  quarantine_deaths=2),
                          cache=shared_cache) as srv:
            out = net.adopt_journal(srv, victim)
            assert out["quarantined"] == 1
            assert out["poisoned"] == 0
            res = srv.solve(a, b, request_id="r1", timeout=120.0)
    assert res.status == "ok"
    assert checks.residual_norm(a, res.x, b, relative=True) <= GATE
    assert any(ev.get("type") == "quarantine" and ev.get("adopted")
               for ev in rec.events)
    # the death counts crossed journals: the adopter re-journals the
    # evidence under synthetic negative boots (its own real boots start
    # at 1 and must never collide)
    st = durable.scan(str(tmp_path / "survivor"))
    assert any(bl["boot"] < 0 for bl in st.blames)
    assert st.by_rid["r1"]["status"] == "ok"


def test_adopt_journal_rejects_past_k_and_scans_operands(rng, shared_cache,
                                                         tmp_path):
    victim = str(tmp_path / "victim")
    victim2 = str(tmp_path / "victim2")
    a, b = _system(rng, 12)
    _journal_with_admit(victim, a, b, blame_boots=(1, 2, 3))
    bad = a.copy()
    bad[0, 0] = np.inf
    _journal_with_admit(victim2, bad, b, rid="r2")
    with SolverServer(_config(str(tmp_path / "survivor"),
                              quarantine_deaths=2),
                      cache=shared_cache) as srv:
        out = net.adopt_journal(srv, victim)
        assert out["poisoned"] == 1 and out["quarantined"] == 0
        out2 = net.adopt_journal(srv, victim2)
        assert out2["poisoned"] == 1
        r1 = srv.solve(a, b, request_id="r1", timeout=60.0)
        r2 = srv.solve(bad, b, request_id="r2", timeout=60.0)
    assert r1.status == STATUS_POISON and "quarantined" in r1.error
    assert r2.status == STATUS_POISON and "poisoned operands" in r2.error


# -- the supervisor's growth guard -----------------------------------------

def _blame_growth_child(jd, marker, exit_code=113):
    """A jax-free supervise child: first incarnation appends pre-encoded
    blame evidence to the live segment and dies; the respawn exits 0."""
    seg = durable.segment_paths(jd)[-1]
    blame = durable.encode_record({
        "rec": "blame", "schema": durable.JOURNAL_SCHEMA, "boot": 1,
        "ids": [1], "rids": ["r1"], "t_unix": 0.0})
    return (
        "import os, sys\n"
        "open(os.environ['HB'], 'w').write('beat')\n"
        f"m = {marker!r}\n"
        "if not os.path.exists(m):\n"
        "    open(m, 'w').write('x')\n"
        f"    open({seg!r}, 'ab').write({blame!r})\n"
        f"    os._exit({exit_code})\n"
        "sys.exit(0)\n")


def test_supervise_free_respawn_at_quarantine_threshold(rng, tmp_path):
    """A death that pushed a suspect's death count TO the quarantine
    threshold is quarantined: respawned without charging the budget —
    max_restarts=0 still comes home."""
    jd = str(tmp_path / "j")
    a, b = _system(rng, 6)
    _journal_with_admit(jd, a, b)
    hb = str(tmp_path / "hb.json")
    env = dict(os.environ, HB=hb)
    logs = []
    with obs.run() as rec:
        rc = durable.supervise(
            [sys.executable, "-c",
             _blame_growth_child(jd, str(tmp_path / "died_once"))],
            heartbeat_path=hb, max_restarts=0, stall_after_s=60.0,
            env=env, journal_dir=jd, quarantine_deaths=1, log=logs.append)
    assert rc == 0
    assert any("quarantined" in ln for ln in logs)
    assert rec.counters.get("serve.quarantined_respawns") == 1
    assert rec.counters.get("serve.supervisor_restarts", 0) == 0


def test_supervise_charges_death_without_new_evidence(rng, tmp_path):
    """The discrimination: the same crash WITHOUT new threshold-reaching
    evidence charges the budget — max_restarts=0 gives up."""
    jd = str(tmp_path / "j")
    a, b = _system(rng, 6)
    _journal_with_admit(jd, a, b, blame_boots=(1,))  # stale, not growing
    hb = str(tmp_path / "hb.json")
    script = (
        "import os\n"
        "open(os.environ['HB'], 'w').write('beat')\n"
        "os._exit(113)\n")
    rc = durable.supervise(
        [sys.executable, "-c", script], heartbeat_path=hb,
        max_restarts=0, stall_after_s=60.0,
        env=dict(os.environ, HB=hb), journal_dir=jd, quarantine_deaths=1,
        log=lambda _ln: None)
    assert rc == 113


def test_supervise_charges_first_death_below_threshold(rng, tmp_path):
    """Blame growth BELOW the threshold is not quarantine progress —
    every mid-dispatch crash blames its in-flight batch once, and those
    first deaths must still charge the budget (an environmental crasher
    under load would otherwise respawn for free forever)."""
    jd = str(tmp_path / "j")
    a, b = _system(rng, 6)
    _journal_with_admit(jd, a, b)
    hb = str(tmp_path / "hb.json")
    rc = durable.supervise(
        [sys.executable, "-c",
         _blame_growth_child(jd, str(tmp_path / "died_once"))],
        heartbeat_path=hb, max_restarts=0, stall_after_s=60.0,
        env=dict(os.environ, HB=hb), journal_dir=jd, quarantine_deaths=2,
        log=lambda _ln: None)
    assert rc == 113


# -- loadgen poison mix ----------------------------------------------------

def test_loadgen_poison_mix_parse_and_materialize():
    from gauss_tpu.serve import loadgen

    for arg, probe in (("nan/16", np.isnan), ("inf/16", np.isinf)):
        (spec, w), = loadgen.parse_mix(f"poison:{arg}")
        a, _b = loadgen.materialize(spec, np.random.default_rng(0))
        assert probe(a).any() and a.shape == (16, 16)
    (spec, _w), = loadgen.parse_mix("poison:singular/16")
    a, _b = loadgen.materialize(spec, np.random.default_rng(0))
    assert np.isfinite(a).all()
    assert np.linalg.matrix_rank(a) < 16
    for bad in ("poison:bogus/16", "poison:nan/1", "poison:nan"):
        with pytest.raises(ValueError):
            loadgen.parse_mix(bad)


def test_loadgen_counts_poison_separately(rng, shared_cache):
    from gauss_tpu.serve.loadgen import (LoadgenConfig, format_summary,
                                         run_load)

    cfg = LoadgenConfig(mix="random:16*3,poison:nan/16", requests=12,
                        warmup=2, mode="closed", concurrency=2, seed=7,
                        verify_gate=GATE, serve=_config(None))
    with SolverServer(cfg.serve, cache=shared_cache) as srv:
        summary = run_load(srv, cfg)
    c = summary["counts"]
    assert c["poison"] >= 1
    assert c["failed"] == 0 and summary["incorrect"] == 0
    assert c["ok"] + c["poison"] == 12
    assert "poison-rejected" in format_summary(summary)


# -- campaign runner / ingest ----------------------------------------------

@pytest.mark.slow
def test_poisoncheck_case_runner_all_kinds(tmp_path, shared_cache):
    from gauss_tpu.serve import poisoncheck

    cache = poisoncheck._TrippingCache(shared_cache)
    for i, kind in enumerate(poisoncheck.POISON_KINDS):
        out = poisoncheck.run_case(i, 99, GATE, str(tmp_path), kind,
                                   cache=cache)
        assert out["outcome"] == "ok", out


def test_campaign_summary_regress_roundtrip(tmp_path):
    from gauss_tpu.serve.poisoncheck import history_records

    summary = {"kind": "poison_campaign", "cases": 32, "wall_s": 64.0}
    recs = history_records(summary)
    assert {m for m, _v, _u in recs} == {"poison:s_per_case"}
    path = tmp_path / "poison.json"
    path.write_text(json.dumps(summary))
    ingested = regress.ingest_file(path)
    assert {r["metric"] for r in ingested} == {"poison:s_per_case"}
    assert all(r["kind"] == "poison" for r in ingested)


def test_summarize_poison_section(rng, shared_cache, tmp_path):
    from gauss_tpu.obs import summarize

    stream = str(tmp_path / "poison_events.jsonl")
    a, b = _system(rng, 12)
    a[0, 0] = np.nan
    with obs.run(metrics_out=stream, run_id="pz0001"):
        with SolverServer(_config(None), cache=shared_cache) as srv:
            assert srv.solve(a, b, timeout=60.0).status == STATUS_POISON
        obs.emit("poison_campaign", cases=32, violations=0,
                 crash_loops=0, invariant_ok=True)
    events = obs.read_events(stream)
    po = summarize.poison_summary(events)
    assert po["poisoned"] >= 1
    assert po["campaign"]["invariant_ok"] is True
    text = summarize.summarize_run(events, "pz0001")
    assert "poison isolation:" in text
