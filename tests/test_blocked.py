"""Blocked LU path vs the unblocked oracle and numpy."""

import jax.numpy as jnp
import numpy as np
import pytest

from gauss_tpu.core.blocked import (
    BlockedLU,
    gauss_solve_blocked,
    lu_factor_blocked,
    lu_solve,
    solve_refined,
)
from gauss_tpu.core.gauss import gauss_solve
from gauss_tpu.io import synthetic
from gauss_tpu.verify import checks


@pytest.mark.parametrize("n", [8, 16, 33, 100, 128, 200])
def test_blocked_matches_numpy(rng, n):
    a = rng.standard_normal((n, n))
    b = rng.standard_normal(n)
    x = np.asarray(gauss_solve_blocked(a, b, panel=32))
    np.testing.assert_allclose(x, np.linalg.solve(a, b), rtol=1e-8, atol=1e-8)


def test_blocked_matches_unblocked_oracle(rng):
    n = 96
    a = rng.standard_normal((n, n))
    b = rng.standard_normal(n)
    x_blocked = np.asarray(gauss_solve_blocked(a, b, panel=32))
    x_oracle = np.asarray(gauss_solve(a, b, pivoting="partial"))
    np.testing.assert_allclose(x_blocked, x_oracle, rtol=1e-9, atol=1e-10)


def test_internal_pattern_blocked():
    n = 256
    a = synthetic.internal_matrix(n)
    b = synthetic.internal_rhs(n)
    x = np.asarray(gauss_solve_blocked(a, b))
    assert checks.internal_pattern_ok(x, atol=1e-7)


def test_factor_reuse_multiple_rhs(rng):
    n = 64
    a = rng.standard_normal((n, n))
    fac = lu_factor_blocked(a, panel=32)
    for _ in range(3):
        b = rng.standard_normal(n)
        x = np.asarray(lu_solve(fac, b))
        np.testing.assert_allclose(x, np.linalg.solve(a, b), rtol=1e-8, atol=1e-9)


def test_permutation_is_valid(rng):
    n = 48
    a = rng.standard_normal((n, n))
    fac = lu_factor_blocked(a, panel=16)
    perm = np.asarray(fac.perm)
    assert sorted(perm.tolist()) == list(range(len(perm)))


def test_lu_reconstruction(rng):
    """P A = L U holds on the padded factor."""
    n = 64
    a = rng.standard_normal((n, n))
    fac = lu_factor_blocked(a, panel=32)
    m = np.asarray(fac.m)
    perm = np.asarray(fac.perm)
    L = np.tril(m, -1) + np.eye(m.shape[0])
    U = np.triu(m)
    a_pad = np.eye(m.shape[0])
    a_pad[:n, :n] = a
    np.testing.assert_allclose(L @ U, a_pad[perm], rtol=1e-9, atol=1e-9)


def test_min_abs_pivot_singular():
    a = np.ones((16, 16))
    b = np.ones(16)
    fac = lu_factor_blocked(a, panel=8)
    assert float(fac.min_abs_pivot) < 1e-12


def test_refined_f32_meets_residual_bar(rng):
    """f32 factorization + refinement meets ||Ax-b|| < 1e-4 (BASELINE bar)."""
    n = 512
    a = synthetic.internal_matrix(n)
    b = synthetic.internal_rhs(n)
    x, _ = solve_refined(a, b, iters=2)
    assert checks.residual_norm(a, x, b) < 1e-4
    assert checks.internal_pattern_ok(x, atol=1e-5)


def test_blocked_f32_dtype(rng):
    n = 64
    a = (rng.standard_normal((n, n)) + n * np.eye(n)).astype(np.float32)
    b = rng.standard_normal(n).astype(np.float32)
    x = gauss_solve_blocked(a, b, panel=32)
    assert x.dtype == np.float32


def test_refined_tol_early_exit_and_staged_devices(rng):
    """tol stops refinement once the residual meets it; pre-staged device
    operands (a_dev/b_dev) give the same solution as host operands."""
    import jax.numpy as jnp

    n = 96
    a = synthetic.internal_matrix(n)
    b = synthetic.internal_rhs(n)
    # Generous tol: converges before exhausting a large iteration budget.
    x_tol, _ = solve_refined(a, b, iters=50, tol=1e-5)
    assert checks.residual_norm(a, x_tol, b) <= 1e-4
    x_ref, _ = solve_refined(a, b, iters=2)
    a_dev = jnp.asarray(a, jnp.float32)
    b_dev = jnp.asarray(b, jnp.float32)
    x_staged, _ = solve_refined(a, b, iters=2, a_dev=a_dev, b_dev=b_dev)
    np.testing.assert_array_equal(x_staged, x_ref)


@pytest.mark.parametrize("panel_impl", ["jax", "pallas"])
@pytest.mark.parametrize("n,panel", [(96, 32), (256, 128), (300, 128)])
def test_unrolled_matches_looped(rng, n, panel, panel_impl):
    """lu_factor_blocked_unrolled: same pivots and factors as the fori_loop
    version (identical math, static shrinking slices) — for both panel
    implementations (the pallas one runs in interpret mode on CPU; it is the
    production bench path on TPU)."""
    from gauss_tpu.core.blocked import lu_factor_blocked_unrolled

    a = rng.standard_normal((n, n)).astype(np.float32)
    f_loop = lu_factor_blocked(a, panel=panel, panel_impl=panel_impl)
    f_unroll = lu_factor_blocked_unrolled(a, panel=panel,
                                          panel_impl=panel_impl)
    # Same math, different GEMM accumulation shapes (masked full-size vs true
    # triangular slices) — f32 noise can in principle flip a near-tie pivot
    # contest, so factor comparison is gated on the perms agreeing; the solve
    # check below is the unconditional correctness oracle.
    if np.array_equal(np.asarray(f_loop.perm), np.asarray(f_unroll.perm)):
        np.testing.assert_allclose(np.asarray(f_loop.m),
                                   np.asarray(f_unroll.m),
                                   rtol=1e-3, atol=1e-4)
    b = rng.standard_normal(n).astype(np.float32)
    x = np.asarray(lu_solve(f_unroll, b), np.float64)
    ref = np.linalg.solve(a.astype(np.float64), b.astype(np.float64))
    np.testing.assert_allclose(x, ref, rtol=5e-3, atol=5e-3)


def test_gauss_solve_blocked_unroll_flag(rng):
    n = 64
    a = rng.standard_normal((n, n))
    b = rng.standard_normal(n)
    x_t = np.asarray(gauss_solve_blocked(a, b, panel=32, unroll=True))
    x_f = np.asarray(gauss_solve_blocked(a, b, panel=32, unroll=False))
    np.testing.assert_allclose(x_t, x_f, rtol=1e-10, atol=1e-10)


def test_triangular_inverses_identity(rng):
    """unit_lower_inv / upper_inv: recursive TRTRI correctness incl. odd
    sizes crossing the recursion base."""
    from gauss_tpu.core.blocked import TRI_INV_BASE, unit_lower_inv, upper_inv

    for p in (1, 7, TRI_INV_BASE, TRI_INV_BASE + 1, 2 * TRI_INV_BASE + 3):
        l = np.tril(rng.standard_normal((p, p)), -1).astype(np.float32) * 0.3 \
            + np.eye(p, dtype=np.float32)
        li = np.asarray(unit_lower_inv(jnp.asarray(l)))
        np.testing.assert_allclose(li @ l, np.eye(p), atol=5e-4)
        u = np.triu(rng.standard_normal((p, p))).astype(np.float32) \
            + np.eye(p, dtype=np.float32) * 4
        ui = np.asarray(upper_inv(jnp.asarray(u)))
        np.testing.assert_allclose(ui @ u, np.eye(p), atol=5e-4)


def test_lu_solve_substitution_fallback(rng):
    """A BlockedLU without stored inverses must still solve (substitution
    path) and agree with the inverse-based solve."""
    from gauss_tpu.core.blocked import BlockedLU, lu_factor_blocked_unrolled

    n = 96
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal(n).astype(np.float32)
    fac = lu_factor_blocked_unrolled(a, panel=32)
    assert fac.linv is not None and fac.linv.shape == (3, 32, 32)
    bare = BlockedLU(m=fac.m, perm=fac.perm, min_abs_pivot=fac.min_abs_pivot)
    x_inv = np.asarray(lu_solve(fac, b), np.float64)
    x_sub = np.asarray(lu_solve(bare, b), np.float64)
    ref = np.linalg.solve(a.astype(np.float64), b.astype(np.float64))
    np.testing.assert_allclose(x_inv, ref, rtol=5e-3, atol=5e-3)
    np.testing.assert_allclose(x_sub, ref, rtol=5e-3, atol=5e-3)


def test_lu_solve_multi_rhs(rng):
    """One factorization, a block of right-hand sides — both solve paths."""
    from gauss_tpu.core.blocked import BlockedLU, lu_factor_blocked_unrolled

    n, k = 96, 5
    a = rng.standard_normal((n, n)).astype(np.float32)
    bs = rng.standard_normal((n, k)).astype(np.float32)
    fac = lu_factor_blocked_unrolled(a, panel=32)
    ref = np.linalg.solve(a.astype(np.float64), bs.astype(np.float64))
    x = np.asarray(lu_solve(fac, bs), np.float64)
    assert x.shape == (n, k)
    np.testing.assert_allclose(x, ref, rtol=5e-3, atol=5e-3)
    bare = BlockedLU(m=fac.m, perm=fac.perm, min_abs_pivot=fac.min_abs_pivot)
    np.testing.assert_allclose(np.asarray(lu_solve(bare, bs), np.float64),
                               ref, rtol=5e-3, atol=5e-3)
    # column i of the block solve == the vector solve of column i, up to
    # f32 reduction-order noise (matvec vs GEMM lowering).
    xi = np.asarray(lu_solve(fac, bs[:, 2]), np.float64)
    np.testing.assert_allclose(x[:, 2], xi, rtol=1e-4, atol=1e-4)


def test_gauss_solve_blocked_vmap(rng):
    """Batched systems via vmap — a TPU-native capability the reference's
    one-process-one-solve design cannot express."""
    import jax

    from gauss_tpu.core.blocked import gauss_solve_blocked

    nb, n = 4, 48
    a = rng.standard_normal((nb, n, n)).astype(np.float32)
    b = rng.standard_normal((nb, n)).astype(np.float32)
    xs = np.asarray(jax.vmap(
        lambda ai, bi: gauss_solve_blocked(ai, bi, panel=16,
                                           panel_impl="jax", unroll=True)
    )(a, b), np.float64)
    for i in range(nb):
        ref = np.linalg.solve(a[i].astype(np.float64), b[i].astype(np.float64))
        np.testing.assert_allclose(xs[i], ref, rtol=5e-3, atol=5e-3)


@pytest.mark.parametrize("chunk", [1, 2, 3, 8])
@pytest.mark.parametrize("panel_impl", ["jax", "pallas"])
def test_chunked_matches_unrolled(rng, chunk, panel_impl):
    """Group-chunked factorization: same solve as the other formulations,
    stored inverses present, for aligned and ragged group counts, on BOTH
    panel implementations (pallas in interpret mode is the production
    TPU path: resolve_factor auto at n > UNROLL_MAX_N)."""
    from gauss_tpu.core.blocked import lu_factor_blocked_chunked

    n = 150  # pads to 5 panels of 32; chunk=2/3 exercise ragged groups
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal(n).astype(np.float32)
    fac = lu_factor_blocked_chunked(a, panel=32, chunk=chunk,
                                    panel_impl=panel_impl)
    assert fac.linv.shape == (5, 32, 32)
    x = np.asarray(lu_solve(fac, b), np.float64)
    ref = np.linalg.solve(a.astype(np.float64), b.astype(np.float64))
    np.testing.assert_allclose(x, ref, rtol=5e-3, atol=5e-3)


def test_chunked_strip_form_multi_strip_and_tail(rng, monkeypatch):
    """The deferred right-of-group update runs in GROUP_UPDATE_STRIP-row
    strips (HBM-transient bound; the unstripped form OOMed at n=32768).
    At production sizes on CPU that path is a single strip, so shrink the
    strip to force several full strips plus a ragged tail — the strip
    arithmetic must be invisible in the result."""
    from gauss_tpu.core import blocked

    n = 200  # pads to 7 panels of 32; chunk 2 -> groups of 64 columns
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal(n).astype(np.float32)
    import jax

    fac_ref = blocked.lu_factor_blocked_chunked(a, panel=32, chunk=2)
    fac_ref = jax.tree.map(np.asarray, fac_ref)  # hold values, not buffers
    monkeypatch.setattr(blocked, "GROUP_UPDATE_STRIP", 48)  # strips + tail
    # The unstripped gate must ALSO be forced off: npad=224 sits far below
    # the unstripped byte bound, so without this the strip constant is
    # never read and the test trivially compares identical programs.
    monkeypatch.setattr(blocked, "GROUP_UPDATE_UNSTRIPPED_MAX_BYTES", 0)
    # The strip width is a trace-time constant, not a jit static arg: a
    # cached executable for this signature would silently ignore the patch
    # and make the test vacuous.
    jax.clear_caches()
    fac_strip = blocked.lu_factor_blocked_chunked(a, panel=32, chunk=2,
                                                  panel_impl="jax")
    # Same math, different loop carving: factors agree to f32 noise (the
    # jax/pallas-interpret panel impls are numerically identical, and the
    # strip boundaries change no accumulation order inside any dot).
    np.testing.assert_allclose(np.asarray(fac_strip.m),
                               np.asarray(fac_ref.m), rtol=2e-4, atol=2e-4)
    x = np.asarray(lu_solve(fac_strip, b), np.float64)
    ref = np.linalg.solve(a.astype(np.float64), b.astype(np.float64))
    np.testing.assert_allclose(x, ref, rtol=5e-3, atol=5e-3)


def test_resolve_factor_forced_modes():
    """Explicit unroll requests are never second-guessed; bad ones raise.
    (Was shadowed by a same-named test below until round 3.)"""
    from gauss_tpu.core import blocked

    assert blocked.resolve_factor(64, True) is blocked.lu_factor_blocked_unrolled
    assert blocked.resolve_factor(64, False) is blocked.lu_factor_blocked
    assert (blocked.resolve_factor(64, "chunked")
            is blocked.lu_factor_blocked_chunked)
    with pytest.raises(ValueError, match="unroll"):
        blocked.resolve_factor(64, "bogus")


def test_chunked_rejects_bad_chunk():
    from gauss_tpu.core.blocked import lu_factor_blocked_chunked

    with pytest.raises(ValueError, match="chunk"):
        lu_factor_blocked_chunked(np.eye(8, dtype=np.float32), panel=8,
                                  chunk=0)


def test_auto_panel_vmem_budget():
    from gauss_tpu.core.blocked import auto_panel

    assert auto_panel(2048) == 256
    # panel=None resolves through auto_panel at every entry point
    from gauss_tpu.core.blocked import lu_factor_blocked_unrolled

    fac = lu_factor_blocked_unrolled(np.eye(64, dtype=np.float32), panel=None)
    assert fac.linv.shape[1] == 128 or fac.m.shape[0] == 128
    assert auto_panel(512) == 128          # below the 1024 crossover
    assert auto_panel(2048) == 256         # end-to-end winner to ~12.4k
    # Round 5 final policy: 128 everywhere past 256's ceiling. The full
    # (n, 128) block stops fitting at ~21.1k but the width stays 128:
    # the chunked route resolves the impl per GROUP, so only the tallest
    # groups run the stock-JAX panel (measured: mixed-128 beats all-64 at
    # every probed top size — 0.79 vs 1.02 s at 24576).
    for n in (17758, 24576, 32768, 34048, 60000):
        assert auto_panel(n) == 128
    from gauss_tpu.core.blocked import panel_fits_vmem

    for n in (100, 1024, 17758, 20480):
        assert panel_fits_vmem(n, auto_panel(n))
    # The tall-group band: the returned width deliberately does NOT fit at
    # full height; per-group resolution covers it.
    assert not panel_fits_vmem(24576, 128)
    assert panel_fits_vmem(20480, 128)
    assert panel_fits_vmem(34048, 64)      # the explicit-64 path still works


def test_lu_solve_substitution_method(rng):
    """method='substitution' must agree with the inverse-based route (the
    stability escape hatch for adversarial systems, ADVICE round 1)."""
    import jax.numpy as jnp

    from gauss_tpu.core import blocked

    n = 100
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    b = rng.standard_normal(n)
    fac = blocked.lu_factor_blocked(jnp.asarray(a), panel=16)
    x_inv = np.asarray(blocked.lu_solve(fac, jnp.asarray(b)))
    x_sub = np.asarray(blocked.lu_solve(fac, jnp.asarray(b),
                                        method="substitution"))
    ref = np.linalg.solve(a, b)
    np.testing.assert_allclose(x_inv, ref, rtol=1e-9, atol=1e-9)
    np.testing.assert_allclose(x_sub, ref, rtol=1e-9, atol=1e-9)
    with pytest.raises(ValueError):
        blocked.lu_solve(fac, jnp.asarray(b), method="bogus")


def test_auto_panel_no_ceiling():
    """auto_panel must not raise at any size (VERDICT r1 #8): it returns
    128 and the per-group panel-impl resolution hands heights past the
    kernel budget to the stock-JAX panel, which has no VMEM limit."""
    from gauss_tpu.core import blocked

    assert blocked.auto_panel(65536) == 128
    assert not blocked.panel_fits_vmem(65536, 128)
    assert blocked.panel_fits_vmem(34048, 64)
    assert blocked.panel_fits_vmem(2048, 256)


def test_explicit_pallas_mosaic_failure_reraises_sizing_hint():
    """ADVICE r5 #2: where the VMEM probe table is incomplete, a raw
    Mosaic scoped-VMEM compile failure on an EXPLICIT pallas request must
    re-raise as the documented sizing ValueError (original chained) — the
    clear-error contract holds outside the probe table too."""
    from gauss_tpu.core import blocked

    assert blocked._looks_like_scoped_vmem_error(RuntimeError(
        "RESOURCE_EXHAUSTED: Ran out of memory in memory space vmem"))
    assert blocked._looks_like_scoped_vmem_error(RuntimeError(
        "Mosaic failed: exceeds available scoped vmem"))
    assert not blocked._looks_like_scoped_vmem_error(RuntimeError("boom"))

    @blocked._reraise_scoped_vmem
    def fake_factor(a, panel_impl="auto"):
        raise RuntimeError(
            "RESOURCE_EXHAUSTED: Ran out of memory in memory space vmem "
            "while compiling the panel kernel")

    with pytest.raises(ValueError, match="scoped VMEM") as ei:
        fake_factor(np.eye(4, dtype=np.float32), panel_impl="pallas")
    assert isinstance(ei.value.__cause__, RuntimeError)
    # auto-mode failures pass through untouched (auto never requests the
    # kernel past the table; a raw error there is a different bug)
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        fake_factor(np.eye(4, dtype=np.float32), panel_impl="auto")


def test_resolve_panel_impl_vmem_fallback(monkeypatch):
    import jax

    from gauss_tpu.core import blocked

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert blocked._resolve_panel_impl("auto", 2048, 256) == "pallas"
    # Round 5: in-kernel pivoting covers the whole single-chip range
    # (aliased kernel, panel 64 to ~37.3k); the stock-JAX fallback engages
    # only past that, academic on one chip.
    assert blocked._resolve_panel_impl("auto", 32768, 64) == "pallas"
    assert blocked._resolve_panel_impl("auto", 65536, 64) == "jax"
    # An explicit pallas request past the ceiling raises a sizing error on
    # a real TPU (ADVICE r3) instead of dying in Mosaic.
    with pytest.raises(ValueError, match="VMEM budget"):
        blocked._resolve_panel_impl("pallas", 65536, 64)
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    assert blocked._resolve_panel_impl("auto", 2048, 256) == "jax"
    # Off-TPU the kernel runs in interpret mode (no VMEM limit): explicit
    # requests are never overridden or rejected.
    assert blocked._resolve_panel_impl("pallas", 65536, 64) == "pallas"


def test_lu_solve_scan_form_matches_unrolled(rng):
    """Above LU_SOLVE_UNROLL_MAX_NB blocks lu_solve switches to the
    lax.scan blockwise form (round 3: the unrolled trace at nb=139 inside
    the ds pipeline defeated the tunneled compiler); both forms and the
    substitution path must agree."""
    from gauss_tpu.core import blocked

    panel = 8
    n = panel * (blocked.LU_SOLVE_UNROLL_MAX_NB + 3)  # forces the scan form
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    x_true = rng.standard_normal(n)
    b = a @ x_true
    fac = blocked.lu_factor_blocked_unrolled(
        jnp.asarray(a, jnp.float32), panel=panel)
    x_scan = np.asarray(blocked.lu_solve(fac, jnp.asarray(b, jnp.float32)))
    x_sub = np.asarray(blocked.lu_solve(fac, jnp.asarray(b, jnp.float32),
                                        method="substitution"))
    np.testing.assert_allclose(x_scan, x_true, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(x_scan, x_sub, rtol=1e-4, atol=1e-4)
    # Multi-RHS rides the same scan.
    b2 = np.stack([b, 2 * b], axis=1)
    x2 = np.asarray(blocked.lu_solve(fac, jnp.asarray(b2, jnp.float32)))
    np.testing.assert_allclose(x2[:, 0] * 2, x2[:, 1], rtol=1e-5, atol=1e-4)


class _FakeDevice:
    def __init__(self, stats):
        self._stats = stats

    def memory_stats(self):
        return self._stats


def test_device_memory_budget_runtime_reported(monkeypatch):
    """The runtime-reported branch (VERDICT r2 weak #6): when the device
    reports bytes_limit, the budget is 85% of it; when the report is
    missing, empty, or raises, the conservative constant applies."""
    import jax

    from gauss_tpu.core import blocked

    monkeypatch.setattr(jax, "devices",
                        lambda *a: [_FakeDevice({"bytes_limit": 16 * 2**30})])
    assert blocked.device_memory_budget() == int(0.85 * 16 * 2**30)

    monkeypatch.setattr(jax, "devices", lambda *a: [_FakeDevice({})])
    assert blocked.device_memory_budget() == blocked.DEFAULT_CHIP_BYTES

    monkeypatch.setattr(jax, "devices", lambda *a: [_FakeDevice(None)])
    assert blocked.device_memory_budget() == blocked.DEFAULT_CHIP_BYTES

    def boom(*a):
        raise RuntimeError("backend gone")

    monkeypatch.setattr(jax, "devices", boom)
    assert blocked.device_memory_budget() == blocked.DEFAULT_CHIP_BYTES


def test_device_memory_budget_direct_paths(monkeypatch):
    """The remaining fallback corners, directly (ISSUE 13 satellite —
    out-of-core admission now hangs off this number): a zero/falsy
    bytes_limit and memory_stats() ITSELF raising (not just jax.devices)
    both fall back to the conservative constant."""
    import jax

    from gauss_tpu.core import blocked

    monkeypatch.setattr(jax, "devices",
                        lambda *a: [_FakeDevice({"bytes_limit": 0})])
    assert blocked.device_memory_budget() == blocked.DEFAULT_CHIP_BYTES

    class _SickDevice:
        def memory_stats(self):
            raise RuntimeError("stats unavailable")

    monkeypatch.setattr(jax, "devices", lambda *a: [_SickDevice()])
    assert blocked.device_memory_budget() == blocked.DEFAULT_CHIP_BYTES


def test_fits_single_chip_uses_runtime_budget(monkeypatch):
    """fits_single_chip threads the runtime-reported budget: 3 copies of
    the f32 working set against 85% of bytes_limit."""
    import jax

    from gauss_tpu.core import blocked

    monkeypatch.setattr(jax, "devices",
                        lambda *a: [_FakeDevice({"bytes_limit": 16 * 2**30})])
    budget = blocked.device_memory_budget()
    # The v5e-class ceiling: n ~ 34.8k at a full 16 GiB report.
    n_max = int((budget / 12) ** 0.5)
    assert blocked.fits_single_chip(n_max)
    assert not blocked.fits_single_chip(n_max + 512)


def test_solve_handoff_routes_by_size(rng):
    """Tiny budget forces the handoff to the sharded blocked engine on the
    CPU mesh; a fitting budget keeps the single-chip refined path."""
    from gauss_tpu.core import blocked

    n = 96
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    x_true = rng.standard_normal(n)
    b = a @ x_true

    x = blocked.solve_handoff(a, b, budget=2**40)  # fits: refined path
    np.testing.assert_allclose(x, x_true, rtol=1e-8, atol=1e-8)

    # Past the budget: the sharded engine, now REFINED (ADVICE round 2 —
    # the raw f32 distributed solution would only reach ~1e-4 here; host-f64
    # refinement through the distributed factors restores f64-grade accuracy,
    # so the contract no longer degrades at the routing boundary).
    x = blocked.solve_handoff(a, b, budget=1024)
    np.testing.assert_allclose(x, x_true, rtol=1e-8, atol=1e-8)


def test_solve_handoff_single_device_streams(rng, monkeypatch):
    """An oversized request with NO multi-device mesh now STREAMS through
    the out-of-core engine instead of raising (ISSUE 13 — the explicit
    error stopped being a capability); the typed sizing error remains only
    when the host cannot admit the system either."""
    from gauss_tpu import obs, outofcore
    from gauss_tpu.core import blocked
    from gauss_tpu.dist.mesh import make_mesh
    from gauss_tpu.outofcore import stream as ooc_stream

    n = 96
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    x_true = rng.standard_normal(n)
    b = a @ x_true
    with obs.run() as rec:
        x = blocked.solve_handoff(a, b, budget=16, mesh=make_mesh(1))
    np.testing.assert_allclose(x, x_true, rtol=1e-8, atol=1e-8)
    routes = [e for e in rec.events if e["type"] == "route"]
    assert routes and routes[-1]["lane"] == "outofcore"

    # Host cannot hold it either -> the explicit sizing error survives.
    monkeypatch.setattr(ooc_stream, "host_memory_budget", lambda: 16)
    assert not outofcore.outofcore_fits(n)
    with pytest.raises(ValueError, match="single-chip budget"):
        blocked.solve_handoff(a, b, budget=16, mesh=make_mesh(1))


def test_resolve_factor_policy(monkeypatch):
    """Size policy incl. the large-n compile-payload fallback (r2): chunked
    group counts beyond MAX_CHUNK_GROUPS route to the flat fori program."""
    import jax

    from gauss_tpu.core import blocked

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert blocked.resolve_factor(2048, "auto") is blocked.lu_factor_blocked_unrolled
    assert blocked.resolve_factor(8192, "auto") is blocked.lu_factor_blocked_chunked
    assert blocked.resolve_factor(12288, "auto") is blocked.lu_factor_blocked_chunked
    # Compile payload scales with GROUP count: 35 chunk-4 groups at n=17758
    # did not compile in 49 min on the tunneled chip (the round-2 memplus
    # crash); the chunk ESCALATES so the group count stays under the cap.
    f = blocked.resolve_factor(16384, "auto")
    assert getattr(f, "func", f) is blocked.lu_factor_blocked_chunked
    assert f.keywords["chunk"] == 8
    f = blocked.resolve_factor(17758, "auto")
    assert getattr(f, "func", f) is blocked.lu_factor_blocked_chunked
    assert f.keywords["chunk"] == 8
    # Round-5 top band at panel 128: 24576 runs 192 blocks at chunk 8
    # (24 groups, the measured-best config); 32768/34048 escalate to 32 —
    # the chunk-16 rung is skipped at panel 128 (its W=2048 groups trip
    # the aliasing fusion double-count; round-5 compile probes). The
    # chunked route covers the whole single-chip range — the flat fori
    # fallback never routes below the HBM ceiling (VERDICT r3 next #2).
    f = blocked.resolve_factor(24576, "auto")  # panel 128 -> 192 blocks
    assert getattr(f, "func", f) is blocked.lu_factor_blocked_chunked
    assert f.keywords["chunk"] == 8
    for big_n in (32768, 34048):
        f = blocked.resolve_factor(big_n, "auto")
        assert getattr(f, "func", f) is blocked.lu_factor_blocked_chunked
        assert f.keywords["chunk"] == 32
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    # PR-10 reclaim: off-TPU backends share the size policy — true
    # triangular work wins wherever FLOPs are paid linearly (measured
    # 1.43 -> 0.66 s at n=2048 on the CPU proxy); only sub-1024 systems
    # keep the flat one-traced-body form (test-mesh sizes, where compile
    # time dominates).
    assert blocked.resolve_factor(512, "auto") is blocked.lu_factor_blocked
    assert (blocked.resolve_factor(2048, "auto")
            is blocked.lu_factor_blocked_unrolled)
    f = blocked.resolve_factor(24576, "auto")
    assert getattr(f, "func", f) is blocked.lu_factor_blocked_chunked
    assert f.keywords["chunk"] == 8
    # The donating twins ride the same policy (resolve_factor's
    # fast-path contract): same route, buffer-donating executable.
    assert (blocked.resolve_factor(512, "auto", donate=True)
            is blocked.lu_factor_blocked_donating)
    assert (blocked.resolve_factor(2048, "auto", donate=True)
            is blocked.lu_factor_blocked_unrolled_donating)


def test_gauss_solve_blocked_multi_rhs_shapes(rng):
    """Serving stacks RHS columns: the one-jit factor+solve path must take
    (n,) and (n, k) with shape-preserving returns (the multi-RHS hardening
    behind gauss_tpu.serve's batched lane)."""
    n, k = 48, 3
    a = (rng.standard_normal((n, n)) + n * np.eye(n)).astype(np.float32)
    bs = rng.standard_normal((n, k)).astype(np.float32)
    ref = np.linalg.solve(a.astype(np.float64), bs.astype(np.float64))
    x = np.asarray(gauss_solve_blocked(a, bs, panel=16))
    assert x.shape == (n, k)
    np.testing.assert_allclose(x, ref, rtol=5e-3, atol=5e-3)
    xv = np.asarray(gauss_solve_blocked(a, bs[:, 0], panel=16))
    assert xv.shape == (n,)
    np.testing.assert_allclose(xv, ref[:, 0], rtol=5e-3, atol=5e-3)


def test_solve_refined_multi_rhs(rng):
    """Refinement's host-f64 residual loop carries the k axis: the f64
    result must hit the same residual bar per column as the vector path."""
    n, k = 64, 4
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    bs = rng.standard_normal((n, k))
    x, fac = solve_refined(a, bs, panel=16, iters=2)
    assert x.shape == (n, k) and x.dtype == np.float64
    assert fac.linv is not None
    ref = np.linalg.solve(a, bs)
    np.testing.assert_allclose(x, ref, rtol=1e-9, atol=1e-9)
    # tol early-exit applies to the whole block (Frobenius residual).
    x2, _ = solve_refined(a, bs, panel=16, iters=8, tol=1e-10)
    np.testing.assert_allclose(x2, ref, rtol=1e-9, atol=1e-9)
    # Vector path unchanged: (n,) in -> (n,) out.
    xv, _ = solve_refined(a, bs[:, 0], panel=16, iters=2)
    assert xv.shape == (n,)


def test_solve_handoff_multi_rhs_and_route_event(rng):
    """The handoff honors (n, k) on the single-chip route and emits its
    routing decision as an obs ``route`` event (the serve-lane trace hook)."""
    from gauss_tpu import obs

    n, k = 48, 2
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    bs = rng.standard_normal((n, k))
    from gauss_tpu.core import blocked

    with obs.run() as rec:
        x = blocked.solve_handoff(a, bs, budget=2**40)
    assert x.shape == (n, k)
    np.testing.assert_allclose(x, np.linalg.solve(a, bs),
                               rtol=1e-8, atol=1e-8)
    routes = [e for e in rec.events if e["type"] == "route"]
    assert len(routes) == 1
    assert routes[0]["tool"] == "solve_handoff"
    assert routes[0]["lane"] == "single_chip"
    assert routes[0]["est_bytes"] == 3 * n * n * 4
    assert routes[0]["budget"] == 2**40
