"""Tests for .dat I/O and synthetic generators (reference C7/C8 parity)."""

import io

import numpy as np
import pytest

from gauss_tpu.io import datfile, synthetic


MATRIX_3 = "3 3 4\n1 1 2\n2 2 5\n3 1 7\n1 3 -1.5\n0 0 0\n"


def test_read_dat_coordinates():
    n, rows, cols, vals = datfile.read_dat(io.StringIO(MATRIX_3))
    assert n == 3
    assert list(rows) == [0, 1, 2, 0]
    assert list(cols) == [0, 1, 0, 2]
    assert list(vals) == [2.0, 5.0, 7.0, -1.5]


def test_read_dat_dense():
    dense = datfile.read_dat_dense(io.StringIO(MATRIX_3))
    expected = np.zeros((3, 3))
    expected[0, 0], expected[1, 1], expected[2, 0], expected[0, 2] = 2, 5, 7, -1.5
    np.testing.assert_array_equal(dense, expected)


def test_missing_terminator_strict_vs_reference():
    """Strict (default) treats a missing `0 0 0` terminator as a truncated
    file; strict=False keeps the reference's EOF-terminated acceptance."""
    with pytest.raises(datfile.DatFormatError, match="terminator"):
        datfile.read_dat_dense(io.StringIO("2 2 1\n1 2 4\n"))
    dense = datfile.read_dat_dense(io.StringIO("2 2 1\n1 2 4\n"),
                                   strict=False)
    assert dense[0, 1] == 4.0


def test_truncated_body_raises():
    with pytest.raises(ValueError):
        datfile.read_dat(io.StringIO("2 2 3\n1 1 1\n0 0 0\n"))


def test_roundtrip(tmp_path, rng):
    a = rng.standard_normal((7, 7))
    p = tmp_path / "m.dat"
    datfile.write_dat(p, a)
    back = datfile.read_dat_dense(p, engine="python")
    np.testing.assert_allclose(back, a, rtol=1e-5)


def test_write_matches_generator_format(tmp_path):
    """write_dat on generator_matrix reproduces matrix_gen's file shape:
    header n n n*n, column-major body, 0 0 0 terminator."""
    n = 4
    a = synthetic.generator_matrix(n)
    buf = io.StringIO()
    datfile.write_dat(buf, a)
    lines = buf.getvalue().strip().split("\n")
    assert lines[0] == f"{n} {n} {n * n}"
    assert lines[-1] == "0 0 0"
    # column-major: first n entries are column 1
    first = [line.split() for line in lines[1:1 + n]]
    assert [f[1] for f in first] == ["1"] * n
    # value = 2*min(row, col) 1-indexed: (1,1)->2, (2,1)->2, (3,1)->2
    assert first[0][2] == "2"


def test_internal_equals_generator():
    """The two synthetic families produce the same symmetric min-matrix."""
    np.testing.assert_array_equal(
        synthetic.internal_matrix(6), synthetic.generator_matrix(6))


def test_duplicate_coordinates_strict_vs_reference():
    """Strict (default) rejects duplicate (row, col) entries as corrupt;
    strict=False keeps the reference's last-wins densifying overwrite."""
    text = "2 2 2\n1 1 3\n1 1 9\n0 0 0\n"
    with pytest.raises(datfile.DatFormatError, match="duplicate"):
        datfile.read_dat_dense(io.StringIO(text))
    dense = datfile.read_dat_dense(io.StringIO(text), strict=False)
    assert dense[0, 0] == 9.0


def test_read_dat_fscanf_whitespace_tolerance():
    """The reference parses with fscanf, which accepts arbitrary inter-token
    whitespace (spaces, tabs, blank lines); parity requires the same."""
    from io import StringIO

    text = ("  3   3\t9\n1 1 2.0\n  1\t2   4.0\n1 3 6.0\n2 1 1.0\n"
            "2 2 5.0\n\n2 3 1.5\n3 1 7.0\n3 2 0.5\n3 3 9.0\n0 0 0\n")
    n, r, c, v = datfile.read_dat(StringIO(text))
    assert n == 3 and len(v) == 9
    assert v[1] == 4.0 and (r[1], c[1]) == (0, 1)
