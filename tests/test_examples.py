"""The examples must actually run (small sizes, CPU)."""

import subprocess
import sys
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args):
    return subprocess.run([sys.executable] + args, cwd=REPO,
                          capture_output=True, text=True, timeout=300)


def test_library_quickstart_runs():
    r = _run(["examples/library_quickstart.py", "64"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "pattern ok: True" in r.stdout
    assert "multi-RHS" in r.stdout


def test_distributed_example_runs():
    r = _run(["examples/distributed_solve.py", "64", "4"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "pattern ok = True" in r.stdout


def test_serve_quickstart_runs():
    r = _run(["examples/serve_quickstart.py"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 INCORRECT" in r.stdout
    assert "lane=batched" in r.stdout


def test_fleet_solve_runs():
    r = _run(["examples/fleet_solve.py"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "kill=True stall=True" in r.stdout
    assert "restart" in r.stdout


def test_resilient_solve_runs():
    r = _run(["examples/resilient_solve.py"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "rung=pivot_safe" in r.stdout
    assert "typed UnrecoverableSolveError" in r.stdout
    assert "killed mid-factorization" in r.stdout
    assert "bit-identical to uninterrupted: True" in r.stdout


def test_abft_solve_runs():
    r = _run(["examples/abft_solve.py"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "clean:      rung=abft detections=0" in r.stdout
    assert "replays=1 localized to group(s) [1]" in r.stdout
    assert "bit-identical to clean: True" in r.stdout
    assert "persistent: served by rung=blocked" in r.stdout
    assert "corrected=True" in r.stdout


def test_structured_solve_runs():
    r = _run(["examples/structured_solve.py"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "engine=cholesky" in r.stdout
    assert "engine=banded" in r.stdout
    assert "engine=blockdiag" in r.stdout
    assert "verified, not silently wrong" in r.stdout


def test_tuned_serve_runs():
    r = _run(["examples/tuned_serve.py"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "sweep winner for lu_factor/n64/float32/blocked" in r.stdout
    assert "served 6/6 ok, 0 incorrect" in r.stdout
    assert "store consults during serve warmup: 1" in r.stdout


def test_live_serve_runs():
    r = _run(["examples/live_serve.py"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "gauss_serve_served_total 12" in r.stdout
    assert "slo alert firing = True" in r.stdout
    assert "slo alert cleared after good traffic (1 fired / 1 cleared)" \
        in r.stdout
    assert "0 problem(s) — exactly one terminal each" in r.stdout
    assert "serve_batch_solve" in r.stdout
