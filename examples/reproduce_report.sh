#!/usr/bin/env bash
# Reproduce the core of reports/REPORT.md and graphs/ from scratch (run on
# the TPU host; this subset takes ~30-45 min behind a tunneled dev chip).
# The COMMITTED report also carries the large-n band (16384-34048), the
# per-size matmul cells, and the real-chip dist cells — regenerate those
# with scripts/regen_round5.sh + scripts/assemble_report_round5.sh (a few
# hours). External suites read the REAL reference matrices in place when a
# checkout exists (GAUSS_TPU_REFERENCE_ROOT, default /root/reference) and
# fall back to the deterministic stand-ins otherwise; every cell records
# which one ran.
set -euo pipefail
cd "$(dirname "$0")/.."

python -m gauss_tpu.bench.grid --suite gauss-internal \
    --backends tpu,tpu-unblocked,seq,omp,threads,forkjoin,tiled \
    --json /tmp/gi.json
python -m gauss_tpu.bench.grid --suite gauss-internal \
    --backends tpu,tpu-rowelim,tpu-rowelim-step \
    --span device --json /tmp/gid.json
python -m gauss_tpu.bench.grid --suite gauss-internal --keys 4096,8192 \
    --backends tpu,tpu-rowelim --span device --json /tmp/gil.json
python -m gauss_tpu.bench.grid --suite gauss-external --backends tpu,seq,omp \
    --keys matrix_10,jpwh_991,orsreg_1,sherman5,saylr4,sherman3 \
    --json /tmp/ge.json
python -m gauss_tpu.bench.grid --suite gauss-external --keys memplus \
    --backends tpu --json /tmp/gem.json
python -m gauss_tpu.bench.grid --suite gauss-external --keys memplus \
    --backends tpu --span device --json /tmp/gemd.json
python -m gauss_tpu.bench.grid --suite gauss-external --backends tpu \
    --span device --json /tmp/ged.json
python -m gauss_tpu.bench.grid --suite matmul \
    --backends tpu,tpu-pallas,tpu-pallas-v1,seq,omp --json /tmp/mm.json
python -m gauss_tpu.bench.grid --suite matmul \
    --backends tpu,tpu-pallas,tpu-pallas-v1 --span device --json /tmp/mmd.json
# The MXU precision sweep (HIGHEST vs bf16x3 through the ds-refined chain).
python -m gauss_tpu.bench.precision --sizes 2048,4096,8192 \
    --json /tmp/gprec.json
# The distributed shard sweep runs on a forced virtual CPU mesh and MUST be
# its own process (the forced device count latches at backend init).
JAX_PLATFORMS=cpu python -m gauss_tpu.bench.grid --suite gauss-dist \
    --json /tmp/gdist.json

python -m gauss_tpu.bench.report /tmp/gi.json /tmp/gid.json /tmp/gil.json \
    /tmp/ge.json /tmp/gem.json /tmp/gemd.json /tmp/ged.json /tmp/mm.json \
    /tmp/mmd.json /tmp/gprec.json /tmp/gdist.json \
    --title "gauss-tpu benchmark report" --out reports/REPORT.md --profile 1024
python -m gauss_tpu.bench.plots /tmp/gi.json /tmp/gid.json /tmp/mmd.json \
    --outdir graphs
