#!/usr/bin/env bash
# Reproduce reports/REPORT.md and graphs/ from scratch (run on the TPU host;
# the full sweep takes ~20-30 min behind a tunneled dev chip).
set -euo pipefail
cd "$(dirname "$0")/.."

python -m gauss_tpu.bench.grid --suite gauss-internal \
    --backends tpu,tpu-unblocked,seq,omp,threads,forkjoin,tiled \
    --json /tmp/gi.json
python -m gauss_tpu.bench.grid --suite gauss-internal --backends tpu \
    --span device --json /tmp/gid.json
python -m gauss_tpu.bench.grid --suite gauss-external --backends tpu,seq,omp \
    --json /tmp/ge.json
python -m gauss_tpu.bench.grid --suite gauss-external --backends tpu \
    --span device --json /tmp/ged.json
python -m gauss_tpu.bench.grid --suite matmul \
    --backends tpu,tpu-pallas,tpu-pallas-v1,seq,omp --json /tmp/mm.json
python -m gauss_tpu.bench.grid --suite matmul \
    --backends tpu,tpu-pallas,tpu-pallas-v1 --span device --json /tmp/mmd.json

python -m gauss_tpu.bench.report /tmp/gi.json /tmp/gid.json /tmp/ge.json \
    /tmp/ged.json /tmp/mm.json /tmp/mmd.json \
    --title "gauss-tpu benchmark report" --out reports/REPORT.md --profile 1024
python -m gauss_tpu.bench.plots /tmp/gi.json /tmp/gid.json /tmp/mmd.json \
    --outdir graphs
