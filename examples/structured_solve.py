"""Structure-aware solve quickstart: detect, route, and demote safely.

Run on any backend (CPU works):

    JAX_PLATFORMS=cpu python examples/structured_solve.py

Builds one system per structure class (SPD, tridiagonal, block-diagonal,
dense), shows the detector's classification, and solves each through
``solve_auto`` — the SPD system takes the half-price blocked Cholesky, the
tridiagonal one the O(n) associative-scan Thomas engine, the block-diagonal
one a single vmap-batched dispatch, and the dense one general LU. Then a
LYING structure tag is forced through the fault-injection hook to show the
recovery ladder demoting to general LU with a verified answer instead of
shipping a wrong one. See docs/STRUCTURE.md.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # run from anywhere

from gauss_tpu.utils.env import honor_jax_platforms

honor_jax_platforms()

import numpy as np

from gauss_tpu.io import synthetic
from gauss_tpu.resilience import inject
from gauss_tpu.structure import detect_structure, solve_auto
from gauss_tpu.structure.detect import STRUCTURE_KINDS
from gauss_tpu.verify import checks


def main():
    rng = np.random.default_rng(258458)
    n = 64
    systems = {
        "spd": synthetic.spd_matrix(n),
        "banded": synthetic.banded_matrix(n, 1),
        "blockdiag": synthetic.blockdiag_matrix(n, 8),
        "dense": synthetic.dense_matrix(n),
    }

    print("== detect -> route -> engine -> 1e-4 gate ==")
    for name, a in systems.items():
        b = rng.standard_normal(n)
        info = detect_structure(a)
        res = solve_auto(a, b, info=info)
        rel = checks.residual_norm(a, res.x, b, relative=True)
        print(f"  {name:9s} detected={info.kind:9s} "
              f"bandwidth={info.bandwidth:2d} blocks={len(info.blocks):2d} "
              f"-> engine={res.rung:9s} rel_residual={rel:.2e}")
        assert rel <= 1e-4

    print()
    print("== a lying classifier cannot ship a wrong answer ==")
    a = systems["dense"]          # NOT symmetric...
    b = rng.standard_normal(n)
    plan = inject.FaultPlan([inject.FaultSpec(
        site="structure.detect", kind="mistag",
        param=float(STRUCTURE_KINDS.index("spd")),  # ...but tagged SPD
        max_triggers=1)])
    with inject.plan(plan):
        res = solve_auto(a, b)
    rel = checks.residual_norm(a, res.x, b, relative=True)
    print(f"  forced tag=spd on a non-symmetric matrix: Cholesky rejected "
          f"it (typed NotSPDError),")
    print(f"  ladder demoted to engine={res.rung} "
          f"(rung {res.rung_index}), rel_residual={rel:.2e} — "
          f"verified, not silently wrong")
    assert res.recovered and rel <= 1e-4
    print()
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
