"""Serving quickstart: a long-lived batched solver service in ~30 lines.

Run on any backend (CPU works):

    JAX_PLATFORMS=cpu python examples/serve_quickstart.py

Submits a burst of mixed-size systems (plus one multi-RHS block) to a
SolverServer, prints per-request lanes/latencies, then a cache + loadgen
report. See docs/SERVING.md for the architecture and `gauss-serve --help`
for the full load-test harness.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # run from anywhere

from gauss_tpu.utils.env import honor_jax_platforms

honor_jax_platforms()

import numpy as np

from gauss_tpu import obs
from gauss_tpu.serve import ServeConfig, SolverServer
from gauss_tpu.serve.loadgen import LoadgenConfig, format_summary, run_load


def system(rng, n, k=None):
    a = rng.standard_normal((n, n))
    a[np.arange(n), np.arange(n)] += float(n)  # well-conditioned
    b = rng.standard_normal(n) if k is None else rng.standard_normal((n, k))
    return a, b


def main():
    rng = np.random.default_rng(258458)
    cfg = ServeConfig(ladder=(64, 128, 256), max_batch=8,
                      refine_steps=1, verify_gate=1e-4)
    with obs.run(tool="serve_quickstart"):
        with SolverServer(cfg) as srv:
            # A burst of async submissions: same-bucket requests batch into
            # single vmapped device steps; repeated shapes hit the
            # executable cache.
            handles = [srv.submit(*system(rng, n))
                       for n in (50, 60, 120, 64, 200, 120, 50)]
            # Multi-RHS: one factorization, a block of right-hand sides.
            handles.append(srv.submit(*system(rng, 100, k=4)))
            for h in handles:
                res = h.result(timeout=300)
                shape = res.x.shape if res.ok else None
                print(f"  request n={res.x.shape[0] if res.ok else '?'} "
                      f"-> {res.status:8s} lane={res.lane:8s} "
                      f"bucket={res.bucket_n} x{shape} "
                      f"latency={res.latency_s:.4f}s")
            print("cache:", srv.cache.stats())

            # The same server under a small closed-loop load test.
            summary = run_load(srv, LoadgenConfig(
                mix="random:50*2,random:120,internal:64",
                requests=24, warmup=4, concurrency=4, serve=cfg))
    print(format_summary(summary))
    assert summary["incorrect"] == 0


if __name__ == "__main__":
    main()
