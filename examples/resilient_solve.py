"""Resilience quickstart: inject a fault, watch the ladder recover.

Run on any backend (CPU works):

    JAX_PLATFORMS=cpu python examples/resilient_solve.py

Solves the same system three ways — clean, under a one-shot injected NaN
panel corruption (recovered by the pivot-safe re-factor rung), and with
corrupted INPUT (a typed UnrecoverableSolveError: no rung can repair a
system that was never well-posed) — printing the obs `fault`/`recovery`
events each case produced. Then a checkpointed factorization is killed
mid-run and resumed bit-identically. See docs/RESILIENCE.md.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # run from anywhere

from gauss_tpu.utils.env import honor_jax_platforms

honor_jax_platforms()

import numpy as np

from gauss_tpu import obs
from gauss_tpu.resilience import checkpoint, inject, recover


def events_of(rec, *types):
    return [e for e in rec.events if e["type"] in types]


def main():
    rng = np.random.default_rng(258458)
    n = 64
    a = rng.standard_normal((n, n))
    a[np.arange(n), np.arange(n)] += float(n)
    b = rng.standard_normal(n)

    # 1. Healthy solve: rung 0, no recovery noise.
    res = recover.solve_resilient(a, b)
    print(f"clean:     rung={res.rung} attempts={res.attempts} "
          f"rel_residual={res.rel_residual:.2e}")

    # 2. One-shot NaN corruption of the factor operand: rung 0 fails the
    #    finite gate, the pivot-safe re-factor rung recovers.
    plan = inject.FaultPlan.parse("core.blocked.factor=nan:max=1")
    with obs.run(tool="resilient_solve") as rec:
        with inject.plan(plan) as active:
            res = recover.solve_resilient(a, b)
    print(f"nan fault: rung={res.rung} attempts={res.attempts} "
          f"rel_residual={res.rel_residual:.2e} "
          f"(injected: {active.stats()['triggered']})")
    for ev in events_of(rec, "fault", "recovery"):
        kv = {k: v for k, v in ev.items()
              if k in ("site", "kind", "trigger", "rung", "outcome")}
        print(f"  obs {ev['type']}: {kv}")

    # 3. Corrupted input: typed error, never a silent wrong answer.
    bad = a.copy()
    bad[3, 7] = np.nan
    try:
        recover.solve_resilient(bad, b)
    except recover.UnrecoverableSolveError as e:
        print(f"bad input: typed {type(e).__name__} (trigger={e.trigger})")

    # 4. Checkpointed factorization killed between groups, then resumed.
    path = "/tmp/resilient_solve_ck.npz"
    kill = inject.FaultPlan([inject.FaultSpec(
        site="checkpoint.group", kind="raise", max_triggers=1, skip=1)])
    a32 = a.astype(np.float32)
    try:
        with inject.plan(kill):
            checkpoint.lu_factor_blocked_chunked_checkpointed(
                a32, path, panel=16, chunk=2)
    except inject.SimulatedFaultError:
        print(f"checkpoint: killed mid-factorization, carry saved at {path}")
    fac = checkpoint.lu_factor_blocked_chunked_checkpointed(
        a32, path, panel=16, chunk=2)
    clean = checkpoint.lu_factor_blocked_chunked_checkpointed(
        a32, path + ".clean", panel=16, chunk=2)
    identical = all(
        np.array_equal(np.asarray(getattr(fac, f)),
                       np.asarray(getattr(clean, f)))
        for f in ("m", "perm", "min_abs_pivot", "linv", "uinv"))
    print(f"checkpoint: resumed, bit-identical to uninterrupted: "
          f"{identical}")


if __name__ == "__main__":
    main()
