"""Live telemetry quickstart: watch a serving process while it runs.

Run on any backend (CPU works):

    JAX_PLATFORMS=cpu python examples/live_serve.py

Starts a SolverServer with the live plane embedded (ephemeral port),
drives a little traffic, scrapes /metrics over HTTP like a Prometheus
collector would, forces a deadline-violation burst so the SLO burn-rate
alert fires (then clears), and folds the recorded stream into per-request
span trees. See docs/OBSERVABILITY.md ("live telemetry") for the endpoint
table and `gauss-top --help` for the interactive dashboard.
"""

import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # run from anywhere

from gauss_tpu.utils.env import honor_jax_platforms

honor_jax_platforms()

import numpy as np

from gauss_tpu import obs
from gauss_tpu.obs import requesttrace
from gauss_tpu.obs.slo import SLO
from gauss_tpu.serve import ServeConfig, SolverServer


def system(rng, n):
    a = rng.standard_normal((n, n))
    a[np.arange(n), np.arange(n)] += float(n)  # well-conditioned
    return a, rng.standard_normal(n)


def main():
    rng = np.random.default_rng(258458)
    # Tiny SLO windows so the fire/clear cycle fits in an example run.
    slo = SLO(name="serve_ok", objective=0.95, short_window_s=1.5,
              long_window_s=8.0, fire_burn=2.0, clear_burn=1.0, min_count=4)
    cfg = ServeConfig(ladder=(64, 128), max_batch=8, refine_steps=1,
                      verify_gate=1e-4, live_port=0, slos=(slo,))
    with obs.run(tool="live_serve_example") as rec:
        with SolverServer(cfg) as server:
            url = server.live_url
            print(f"live endpoint: {url}  (try: gauss-top --url {url})")

            for _ in range(12):
                a, b = system(rng, rng.choice([48, 100]))
                assert server.solve(a, b).ok

            text = urllib.request.urlopen(url + "/metrics").read().decode()
            print("\n/metrics scrape (excerpt):")
            for line in text.splitlines():
                if line.startswith(("gauss_serve_served_total",
                                    "gauss_serve_latency_s{",
                                    "gauss_slo_firing")):
                    print(f"  {line}")

            print("\nforcing a deadline-violation burst ...")
            for _ in range(10):
                a, b = system(rng, 48)
                server.submit(a, b, deadline_s=1e-6).result(30)
            mon = server.live.slos[0]
            print(f"slo alert firing = {mon.firing} "
                  f"(burn short/long = {mon.burn_rates()[0]:.1f}x / "
                  f"{mon.burn_rates()[1]:.1f}x)")

            time.sleep(slo.short_window_s + 0.2)
            while mon.firing:  # good traffic clears the alert
                a, b = system(rng, 48)
                server.solve(a, b)
            print(f"slo alert cleared after good traffic "
                  f"({mon.alerts} fired / {mon.clears} cleared)")

    trees = requesttrace.request_traces(rec.events)
    problems = requesttrace.check_traces(trees)
    print(f"\nper-request traces: {len(trees)} request(s), "
          f"{len(problems)} problem(s) — exactly one terminal each")
    sample = next(t for t in trees.values() if t["status"] == "ok")
    print(requesttrace.format_tree(sample))


if __name__ == "__main__":
    main()
