"""Tuned serving: sweep once offline, then serve with the winners and a
warm persistent compile cache.

The flow a production deployment runs once per hardware generation:

1. ``gauss-tune`` (here: the runner API) micro-sweeps the blocked-LU
   config space and persists the winners to a store file keyed by this
   environment's fingerprint.
2. Every later process — bench, serve warmup, fleet workers — consults
   the store through ``GAUSS_TUNE_STORE``; with no store nothing changes.
3. The persistent XLA compile cache (``GAUSS_COMPILE_CACHE``) makes the
   SECOND process's warmup run from cached executables: cold-start p99
   and fleet-restart resume latency stop paying the re-jit tax.

Run: ``JAX_PLATFORMS=cpu python examples/tuned_serve.py``
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # run from anywhere

from gauss_tpu.utils.env import honor_jax_platforms  # noqa: E402

honor_jax_platforms()

from gauss_tpu import obs                                  # noqa: E402
from gauss_tpu.serve.admission import ServeConfig          # noqa: E402
from gauss_tpu.serve.server import SolverServer            # noqa: E402
from gauss_tpu.tune import apply, compilecache, runner     # noqa: E402
from gauss_tpu.tune import store as tune_store             # noqa: E402

workdir = tempfile.mkdtemp(prefix="gauss_tuned_serve_")
store_path = os.path.join(workdir, "tune_store.json")

# -- 1. the offline sweep (tiny: 2 panel widths x 1 chunk at n=64) ----------
summary = runner.run_sweep(["lu_factor"], [64], reps=1,
                           axes={"panel": [16, 32], "chunk": [1]})
runner.write_store(summary, store_path)
point = summary["points"][0]
print(f"sweep winner for {point['key']}: {point['best_params']} "
      f"({point['improvement']:.2f}x vs seed)")

# -- 2. install the store + compile cache for this (and any child) process --
os.environ[tune_store.ENV_STORE] = store_path
apply.reset_cache()
compilecache.enable(os.path.join(workdir, "xla_cache"))

# -- 3. serve: warmup consults the store; the cache key is unchanged --------
rng = np.random.default_rng(258458)
with obs.run(metrics_out=None, tool="tuned_serve_example") as rec:
    cfg = ServeConfig(ladder=(32, 64), verify_gate=1e-4)
    with SolverServer(cfg) as server:
        results = []
        for _ in range(6):
            n = int(rng.integers(40, 64))
            a = rng.standard_normal((n, n)) + n * np.eye(n)
            b = rng.standard_normal(n)
            results.append(server.solve(a, b, timeout=60.0))
    ok = sum(r.ok for r in results)
    consults = [e for e in rec.events if e.get("type") == "tune"
                and e.get("source") == "store"]
    tuned_panel = [k for k in server.cache.keys()]
print(f"served {ok}/{len(results)} ok, 0 incorrect "
      f"(every solution 1e-4-verified by the server)")
print(f"store consults during serve warmup: {len(consults)} "
      f"(tuned panel applied inside {len(tuned_panel)} cached "
      f"executable(s))")
print(f"second process would reuse the compile cache at "
      f"{compilecache.cache_dir()}")
