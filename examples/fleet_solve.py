"""Supervised fleet quickstart: a solve that survives losing its workers.

Run on any backend (CPU works):

    JAX_PLATFORMS=cpu python examples/fleet_solve.py

Solves one system three ways under the fleet supervisor
(gauss_tpu.resilience.fleet): clean; with worker 1 KILLED at panel group 2
(the supervisor sees the exit, restarts it, and the replacement resumes
from the sharded coordinated checkpoint); and with worker 1 STALLED forever
(its lease heartbeat goes stale, the supervisor kills and replaces it).
All three runs finish with the BIT-IDENTICAL verified solution — the whole
point of deterministic group steps over checkpointed carry. See
docs/RESILIENCE.md ("Supervised fleet solves").
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # run from anywhere

from gauss_tpu.utils.env import honor_jax_platforms

honor_jax_platforms()

import numpy as np

from gauss_tpu import obs
from gauss_tpu.resilience import fleet


def main() -> int:
    rng = np.random.default_rng(258458)
    n = 64
    a = rng.standard_normal((n, n))
    a[np.arange(n), np.arange(n)] += float(n)   # diagonally dominant
    b = rng.standard_normal(n)
    kw = dict(workers=2, panel=16, chunk=1, stall_after_s=3.0,
              job_timeout_s=150.0)

    with obs.run(tool="fleet_example") as rec:
        print(f"supervised solve, n={n}, 2 workers, checkpoint every panel "
              f"group:")
        clean = fleet.solve_supervised(a, b, **kw)
        print(f"  clean:   rung={clean.rung} restarts={clean.restarts} "
              f"rel_residual={clean.rel_residual:.2e}")

        killed = fleet.solve_supervised(
            a, b, inject="fleet.worker.group=kill:skip=2",
            inject_worker=1, **kw)
        print(f"  killed:  worker 1 killed mid-factorization -> "
              f"rung={killed.rung} restarts={killed.restarts} "
              f"rel_residual={killed.rel_residual:.2e}")

        stalled = fleet.solve_supervised(
            a, b, inject="fleet.worker.group=stall:skip=2",
            inject_worker=1, **kw)
        print(f"  stalled: worker 1 hung mid-factorization -> "
              f"rung={stalled.rung} stall detections={stalled.stalls} "
              f"rel_residual={stalled.rel_residual:.2e}")

    ok_kill = np.array_equal(clean.x, killed.x)
    ok_stall = np.array_equal(clean.x, stalled.x)
    print(f"resumed solutions bit-identical to the clean supervised run: "
          f"kill={ok_kill} stall={ok_stall}")
    fleet_events = [e for e in rec.events if e.get("type") == "fleet"]
    kinds = sorted({e.get("event") for e in fleet_events})
    print(f"supervisor emitted {len(fleet_events)} fleet event(s): {kinds}")
    return 0 if (ok_kill and ok_stall) else 1


if __name__ == "__main__":
    sys.exit(main())
