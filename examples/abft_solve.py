"""ABFT quickstart: detect, localize, and repair silent data corruption
MID-solve with checksum-carrying factorizations.

Run on any backend (CPU works):

    JAX_PLATFORMS=cpu python examples/abft_solve.py

Solves the same system three ways — clean ABFT (checksum verified every
panel group, zero detections), with an injected ON-DEVICE bit flip at a
panel-group boundary (detected by the checksum invariant within that
group, repaired by the localized replay rung, bit-identical to the clean
run), and with PERSISTENT corruption (replay exhausts, the typed error
escalates to the full recovery ladder) — then corrects a single-element
GEMM error in place from the row x column checksum intersection.
See docs/RESILIENCE.md (ABFT section).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # run from anywhere

from gauss_tpu.utils.env import honor_jax_platforms

honor_jax_platforms()

import numpy as np

from gauss_tpu import obs
from gauss_tpu.resilience import abft, inject, recover


def main():
    rng = np.random.default_rng(258458)
    n = 128
    a = rng.standard_normal((n, n))
    a[np.arange(n), np.arange(n)] += float(n)
    b = rng.standard_normal(n)

    # 1. Clean ABFT solve: the checksum rides every panel factor and
    #    trailing GEMM; zero detections, factor bit-identical to the
    #    plain (abft=False) path.
    res = recover.solve_resilient(a, b, abft=True, panel=16)
    print(f"clean:      rung={res.rung} detections="
          f"{res.sdc['detections']} rel_residual={res.rel_residual:.2e}")

    # 2. One transient on-device bit flip at panel group 1: the group's
    #    checksum check catches it, the group replays from the last
    #    verified carry, and the result is bit-identical to the clean run.
    plan = inject.FaultPlan.parse("abft.lu.group=sdc_bitflip:skip=1:max=1")
    with obs.run(tool="abft_solve") as rec:
        with inject.plan(plan) as active:
            res2 = recover.solve_resilient(a, b, abft=True, panel=16)
    print(f"sdc flip:   rung={res2.rung} detections="
          f"{res2.sdc['detections']} replays={res2.sdc['replays']} "
          f"localized to group(s) {res2.sdc['detect_groups']} "
          f"(injected: {active.stats()['triggered']})")
    print(f"            bit-identical to clean: "
          f"{bool(np.array_equal(res.x, res2.x))}")
    for ev in rec.events:
        if ev["type"] in ("sdc", "sdc_inject"):
            kv = {k: v for k, v in ev.items()
                  if k in ("site", "engine", "group", "col", "bit",
                           "magnitude", "action")}
            print(f"  obs {ev['type']}: {kv}")

    # 3. Persistent corruption: replay cannot heal it; the typed
    #    SDCUnrecoverableError escalates to the full recovery ladder,
    #    which still returns a verified solution.
    plan = inject.FaultPlan.parse("abft.lu.group=sdc_bitflip:max=100")
    with inject.plan(plan):
        res3 = recover.solve_resilient(a, b, abft=True, panel=16)
    print(f"persistent: served by rung={res3.rung} (escalations: "
          f"{[r for r, _ in res3.escalations]}) "
          f"rel_residual={res3.rel_residual:.2e}")

    # 4. ABFT matmul: a single corrupted element of C = A @ B is
    #    localized to its (row, col) checksum intersection and corrected
    #    in place.
    am = rng.standard_normal((64, 48)).astype(np.float32)
    bm = rng.standard_normal((48, 56)).astype(np.float32)
    plan = inject.FaultPlan.parse("abft.matmul=sdc_bitflip:max=1")
    with inject.plan(plan):
        c, info = abft.abft_matmul(am, bm)
    print(f"matmul:     detections={info['detections']} "
          f"corrected={info['corrected']} at "
          f"({info['row']}, {info['col']})")


if __name__ == "__main__":
    main()
