"""Library quickstart: solve the reference's two benchmark problems.

Run: python examples/library_quickstart.py [n]
(CPU or TPU; first TPU compile of a new size takes ~20-40 s.)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # run from anywhere

from gauss_tpu.utils.env import honor_jax_platforms

honor_jax_platforms()  # JAX_PLATFORMS=cpu must win over a sitecustomize pin

import numpy as np

from gauss_tpu.core.blocked import solve_refined
from gauss_tpu.io import internal_matrix, internal_rhs, write_dat
from gauss_tpu.io.datfile import read_dat_dense
from gauss_tpu.io.synthetic import manufactured_rhs, manufactured_solution
from gauss_tpu.verify import checks


def main(n: int = 512) -> None:
    # 1. The internal synthetic benchmark (reference *_internal_input):
    #    known closed-form solution (-0.5, 0...0, 0.5).
    a, b = internal_matrix(n), internal_rhs(n)
    x, factors = solve_refined(a, b)  # f32 factor + f64-residual refinement
    print(f"internal n={n}: residual {checks.residual_norm(a, x, b):.2e}, "
          f"pattern ok: {checks.internal_pattern_ok(x)}")

    # 2. The external file flavor (reference *_external_input): write a .dat,
    #    read it back, solve against a manufactured solution X__[i] = i+1.
    import tempfile

    rng = np.random.default_rng(0)
    m = rng.standard_normal((n, n)) + n * np.eye(n)
    with tempfile.NamedTemporaryFile(suffix=".dat", mode="w",
                                     delete=False) as f:
        write_dat(f, m)
    m2 = read_dat_dense(f.name)
    os.unlink(f.name)
    x_true = manufactured_solution(n)
    r = manufactured_rhs(m2, x_true)
    x2, _ = solve_refined(m2, r)
    print(f"external n={n}: max rel error "
          f"{checks.max_rel_error(x2, x_true):.2e}")

    # 3. One factorization, many right-hand sides (getrf/getrs split).
    from gauss_tpu.core.blocked import lu_solve

    bs = rng.standard_normal((n, 4))
    xs = np.asarray(lu_solve(factors, bs.astype(np.float32)))
    print(f"multi-RHS: solved {xs.shape[1]} systems with one factorization")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 512)
