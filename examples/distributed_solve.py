"""Distributed solve over a device mesh (the reference's MPI axis).

Run: python examples/distributed_solve.py [n] [shards]
On a single CPU host this self-assembles virtual devices, exactly like the
test suite; on a TPU slice the same code runs over ICI. For multi-HOST
launches, start the same script on every host with
JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES/JAX_PROCESS_ID set (see
gauss_tpu/dist/multihost.py — the mpirun/hostfile analog).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # run from anywhere

from gauss_tpu.utils.env import honor_jax_platforms

honor_jax_platforms()  # JAX_PLATFORMS=cpu must win over a sitecustomize pin


def main(n: int = 256, shards: int = 8) -> None:
    from gauss_tpu.utils.env import force_host_device_count

    force_host_device_count(shards)

    import jax
    import numpy as np

    from gauss_tpu.dist import gauss_dist, make_mesh
    from gauss_tpu.dist.multihost import maybe_initialize_from_args
    from gauss_tpu.io import internal_matrix, internal_rhs
    from gauss_tpu.verify import checks

    class _Args:  # env-only coordinates; no CLI flags in this example
        coordinator = num_processes = process_id = None

    maybe_initialize_from_args(_Args())
    devs = jax.devices() if len(jax.devices()) >= shards else jax.devices("cpu")
    mesh = make_mesh(shards, devices=devs[:shards])
    a = internal_matrix(n, dtype=np.float32)
    b = internal_rhs(n, dtype=np.float32)
    x = np.asarray(gauss_dist.gauss_solve_dist(a, b, mesh=mesh), np.float64)
    print(f"n={n} over {shards} shards (per-step engine): pattern ok = "
          f"{checks.internal_pattern_ok(x, atol=1e-3)}")

    # The scaling engines: 1-D panel-blocked (collectives per panel), and —
    # when the shard count factors into a grid — the 2-D tournament-pivoted
    # engine (per-chip traffic O(n^2/R + n^2/C), the pod-scale shape).
    from gauss_tpu.dist import gauss_dist_blocked, gauss_dist_blocked2d
    from gauss_tpu.dist.mesh import make_mesh_2d_auto, squarest_factors

    xb = np.asarray(gauss_dist_blocked.gauss_solve_dist_blocked(
        a, b, mesh=mesh), np.float64)
    print(f"n={n} over {shards} shards (panel-blocked): pattern ok = "
          f"{checks.internal_pattern_ok(xb, atol=1e-3)}")
    if squarest_factors(shards)[1] > 1:  # shard count factors into a grid
        mesh2 = make_mesh_2d_auto(shards, devices=devs[:shards])
        x2 = gauss_dist_blocked2d.gauss_solve_dist_blocked2d_refined(
            a, b, mesh=mesh2)
        print(f"n={n} over {mesh2.devices.shape} grid (2-D tournament, "
              f"refined): pattern ok = "
              f"{checks.internal_pattern_ok(x2, atol=1e-3)}")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 256,
         int(sys.argv[2]) if len(sys.argv) > 2 else 8)
